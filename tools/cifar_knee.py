"""CIFAR reduced-tier savings knee vs pass count (VERDICT round-2 item 8).

At the driver's 320-pass LeNet op-point, reference-pure horizon 1.0 measured
52.97% saved (below the ~60% target) and the 60.85% headline needed the
stabilized trigger. Full scale (3904 passes) reaches 74.9% reference-pure.
This sweep maps where reference-pure crosses 60% on the reduced-tier
miniature — with the vectorized event state machine, more passes now fit
the same driver budget — plus stabilized rows and D-PSGD accuracy twins so
each op-point carries its honest accuracy gap.

Writes artifacts/cifar_knee_r3_cpu.jsonl (one JSON line per config).

Usage: python tools/cifar_knee.py [quick|seeds]
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax


def main() -> None:
    jax.config.update("jax_platforms", "cpu")

    from eventgrad_tpu.data.datasets import load_or_synthesize
    from eventgrad_tpu.models import LeNetCifar
    from eventgrad_tpu.parallel.events import EventConfig
    from eventgrad_tpu.parallel.topology import Ring
    from eventgrad_tpu.train.loop import consensus_params, evaluate, rank0_slice, train

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out_path = os.path.join(repo, "artifacts", "cifar_knee_r3_cpu.jsonl")
    topo = Ring(8)
    quick = len(sys.argv) > 1 and sys.argv[1] == "quick"

    # reduced-tier op-point: LeNet-5 CIFAR (M5), global batch 64, n=1024,
    # lr 1e-2 momentum 0.9, random sampler (bench.py reduced tier)
    n_train, n_test, batch = 1024, 256, 8
    grid = [
        ("eventgrad", 20, 1.0, 0, 0),    # 320 passes: r2's captured op-point
        ("eventgrad", 40, 1.0, 0, 0),    # 640 passes
        ("eventgrad", 60, 1.0, 0, 0),    # 960 passes
        ("eventgrad", 80, 1.0, 0, 0),    # 1280 passes
        ("eventgrad", 40, 1.05, 50, 0),  # stabilized at the larger budgets
        ("eventgrad", 60, 1.05, 50, 0),
        ("dpsgd", 40, None, None, 0),    # accuracy twins
        ("dpsgd", 60, None, None, 0),
    ]
    if quick:
        grid = grid[:1]
    if len(sys.argv) > 1 and sys.argv[1] == "fullscale":
        # the reference CIFAR pass count (244 epochs x 16 steps = 3904
        # passes, dcifar10/event/event.cpp:31-36 scale) on the LeNet
        # miniature: round-3 re-verification of the reference-pure and
        # stabilized full-scale claims with the vectorized event path
        grid = [
            ("eventgrad", 244, 1.0, 0, 0),
            ("eventgrad", 244, 1.05, 50, 0),
            ("dpsgd", 244, None, None, 0),
        ]
    elif len(sys.argv) > 1 and sys.argv[1] == "seeds":
        # seed-robustness of the reduced-tier headline op-point (640-pass
        # stabilized) with per-seed D-PSGD twins
        grid = [
            ("eventgrad", 40, 1.05, 50, 1),
            ("eventgrad", 40, 1.05, 50, 2),
            ("dpsgd", 40, None, None, 1),
            ("dpsgd", 40, None, None, 2),
        ]

    x, y = load_or_synthesize("cifar10", None, "train", n_synth=n_train)
    xt, yt = load_or_synthesize("cifar10", None, "test", n_synth=n_test)
    for algo, epochs, horizon, silence, seed in grid:
        kw = dict(
            epochs=epochs, batch_size=batch, learning_rate=1e-2,
            momentum=0.9, random_sampler=True, log_every_epoch=False,
            seed=seed,
        )
        if algo == "eventgrad":
            kw["event_cfg"] = EventConfig(
                adaptive=True, horizon=horizon, warmup_passes=10,
                max_silence=silence,
            )
        t0 = time.perf_counter()
        state, hist = train(LeNetCifar(), topo, x, y, algo=algo, **kw)
        wall = time.perf_counter() - t0
        cons = consensus_params(state.params)
        stats0 = rank0_slice(state.batch_stats)
        acc = evaluate(LeNetCifar(), cons, stats0, xt, yt)["accuracy"]
        rec = {
            "algo": algo, "epochs": epochs, "seed": seed,
            "passes": epochs * (n_train // (batch * topo.n_ranks)),
            "horizon": horizon, "max_silence": silence,
            "msgs_saved_pct": (
                round(hist[-1]["msgs_saved_pct"], 2)
                if algo == "eventgrad" else None
            ),
            "test_acc": round(acc, 2),
            "wall_s": round(wall, 1),
        }
        with open(out_path, "a") as f:
            f.write(json.dumps(rec) + "\n")
        print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
