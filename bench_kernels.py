"""Kernel microbenchmarks + on-device numerics checks (run by hand; the
driver contract is bench.py).

Times the Pallas kernels against their XLA/jnp twins on the active device
and asserts numerical agreement there — on TPU this is the Mosaic-compiled
path (off-TPU the kernels auto-select interpret mode, see ops/attention.py):

  * flash attention fwd and fwd+bwd vs materialized-score attention, over
    a sweep of sequence lengths;
  * the fused gossip-mix + momentum-SGD update vs the unfused tree-map
    chain, at the flagship ResNet parameter count.

Prints one JSON line per measurement (flushed immediately — a flaky device
tunnel can wedge mid-run and the completed measurements must survive):
{"kernel", "config", "pallas_ms", "xla_ms", "speedup", "max_err"}.

  * the gossip wire leg: one full neighbor exchange (pack -> ppermute ->
    scatter/apply) for dense / masked / compact x {f32, bf16, int8} at the
    MLP and flagship-ResNet parameter geometries, plus a masked-vs-compact
    whole-train-step comparison — real wire bytes next to measured ms,
    written to artifacts/gossip_wire_{platform}.json (the TPU artifact
    lands via tools/tpu_flagship.py running this same selector on-chip);

  * the flat-arena event-engine leg (`arena`): event_propose_pack vs the
    legacy flatten/propose/gate/pack chain, and the masked_wire +
    fused_mix_commit Pallas kernels vs their jnp twins, with max_err
    asserted 0; on TPU the measured speedups land in
    eventgrad_tpu/ops/arena_tuning.json (the kernels' dispatch table).

Usage: python bench_kernels.py [attn|fused|gossip|arena|bucketed|all|tune]
       [--seqs 512,1024,...]
       [--out FILE]   (appends each line to FILE as well as stdout)

`tune` sweeps flash block sizes (128/256/512) per sequence length and mode
against the XLA twin, emits the whole grid, and writes the per-shape
winners to eventgrad_tpu/ops/flash_tuning.json — the dispatch table
flash_attention consults (ops/flash_tuning.py). Run on the real chip;
the table is only written when the active platform is TPU.
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

_OUT_PATH = None


def _emit(rec: dict) -> None:
    line = json.dumps(rec)
    print(line, flush=True)
    if _OUT_PATH:
        with open(_OUT_PATH, "a") as f:
            f.write(line + "\n")


def _time(fn, *args, iters=20, repeats=5):
    """Min over `repeats` timed bursts of `iters` calls: the tunnel to the
    device adds multi-ms hiccups to individual bursts (observed ~2x run-to-
    run swings on identical configs), and the minimum is the estimator
    least biased by them."""
    out = fn(*args)  # compile
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        jax.block_until_ready(out)
        best = min(best, (time.perf_counter() - t0) / iters)
    return 1000 * best


def _max_err(a, b) -> float:
    fa = np.asarray(jax.tree.leaves(a)[0] if not hasattr(a, "dtype") else a,
                    np.float32)
    fb = np.asarray(jax.tree.leaves(b)[0] if not hasattr(b, "dtype") else b,
                    np.float32)
    return float(np.max(np.abs(fa - fb)))


def bench_attention(seqs=(512, 1024, 2048, 4096)):
    from eventgrad_tpu.ops import flash_attention, flash_attention_reference

    b, h, d = 4, 8, 64
    for t in seqs:
        key = jax.random.PRNGKey(0)
        q, k, v = (
            jax.random.normal(jax.random.fold_in(key, i), (b, t, h, d), jnp.bfloat16)
            for i in range(3)
        )
        flash = jax.jit(lambda q, k, v: flash_attention(q, k, v, True))
        ref = jax.jit(lambda q, k, v: flash_attention_reference(q, k, v, True))
        # numerics first (bf16 inputs, f32 accumulation: ~1e-2 agreement)
        err = _max_err(flash(q, k, v).astype(jnp.float32),
                       ref(q, k, v).astype(jnp.float32))
        assert err < 5e-2, f"flash fwd T={t} diverges from XLA twin: {err}"
        ms_f, ms_r = _time(flash, q, k, v), _time(ref, q, k, v)
        _emit({
            "kernel": "flash_attention_fwd", "config": f"B{b}xT{t}xH{h}xD{d}",
            "pallas_ms": round(ms_f, 3), "xla_ms": round(ms_r, 3),
            "speedup": round(ms_r / ms_f, 2), "max_err": err,
        })

        lossf = jax.jit(jax.grad(lambda q: jnp.sum(
            flash_attention(q, k, v, True).astype(jnp.float32) ** 2)))
        lossr = jax.jit(jax.grad(lambda q: jnp.sum(
            flash_attention_reference(q, k, v, True).astype(jnp.float32) ** 2)))
        err = _max_err(lossf(q).astype(jnp.float32),
                       lossr(q).astype(jnp.float32))
        assert err < 5e-1, f"flash bwd T={t} diverges from XLA twin: {err}"
        ms_f, ms_r = _time(lossf, q), _time(lossr, q)
        _emit({
            "kernel": "flash_attention_fwd_bwd", "config": f"B{b}xT{t}xH{h}xD{d}",
            "pallas_ms": round(ms_f, 3), "xla_ms": round(ms_r, 3),
            "speedup": round(ms_r / ms_f, 2), "max_err": err,
        })


def _fused_case(name, p, b_, g, t):
    from eventgrad_tpu.ops import fused_mix_sgd, mix_sgd_reference

    fused = jax.jit(lambda p, b, g, t: fused_mix_sgd(p, b, g, t, 0.01, 0.9, 1 / 3))
    ref = jax.jit(lambda p, b, g, t: mix_sgd_reference(p, b, g, t, 0.01, 0.9, 1 / 3))
    pf, tf = fused(p, b_, g, t)
    pr, tr = ref(p, b_, g, t)
    err = max(
        max(_max_err(a, b) for a, b in zip(jax.tree.leaves(pf), jax.tree.leaves(pr))),
        max(_max_err(a, b) for a, b in zip(jax.tree.leaves(tf), jax.tree.leaves(tr))),
    )
    assert err < 1e-5, f"fused_mix_sgd diverges from XLA twin: {err}"
    ms_f, ms_r = _time(fused, p, b_, g, t), _time(ref, p, b_, g, t)
    speedup = round(ms_r / ms_f, 2)
    _emit({
        "kernel": "fused_mix_sgd", "config": name,
        "pallas_ms": round(ms_f, 3), "xla_ms": round(ms_r, 3),
        "speedup": speedup, "max_err": err,
    })
    return speedup


def bench_fused_update():
    key = jax.random.PRNGKey(1)
    # one lane-aligned mega-leaf: the pure-bandwidth op-point
    n = 17_400_064
    p, b_, g, t = (
        {"w": jax.random.normal(jax.random.fold_in(key, i), (n,))} for i in range(4)
    )
    _fused_case(f"{n/1e6:.1f}M single leaf", p, b_, g, t)

    # lane-aligned but rows % block != 0: the partial trailing block whose
    # masked out-of-bounds stores the kernel layout depends on — numerics
    # must hold compiled on the real chip, not just in interpret mode
    # (round-2 advisor finding)
    n2 = 17_400_064 + 128 * 3
    p2, b2, g2, t2 = (
        {"w": jax.random.normal(jax.random.fold_in(key, 10 + i), (n2,))}
        for i in range(4)
    )
    _fused_case(f"{n2/1e6:.1f}M partial trailing block", p2, b2, g2, t2)

    # the flagship ResNet's real 86-leaf tree: what the train step applies
    # per step (launch overhead + ragged bias/BN leaves included)
    from eventgrad_tpu.models import ResNet18

    model = ResNet18(dtype=jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), jnp.ones((1, 32, 32, 3)))
    p = variables["params"]
    leaves, treedef = jax.tree.flatten(p)

    def like(i):
        sub = jax.random.fold_in(key, i)
        return treedef.unflatten([
            jax.random.normal(jax.random.fold_in(sub, j), x.shape)
            for j, x in enumerate(leaves)
        ])

    tree_speedup = _fused_case(
        "ResNet18-as-coded tree (86 leaves)", p, like(1), like(2), like(3)
    )
    if jax.devices()[0].platform == "tpu":
        # record the measured verdict for the auto-demote policy
        # (ops/fused_tuning.py): a losing tree case must not run in the
        # train step's fused tail
        import os

        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "eventgrad_tpu", "ops", "fused_tuning.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"platform": jax.devices()[0].device_kind,
                       "tree_speedup": tree_speedup}, f, indent=1)
            f.write("\n")
        os.replace(tmp, path)
        _emit({"tuned": path, "tree_speedup": tree_speedup})


def bench_gossip_wire():
    """Time one full gossip exchange per (mode, wire) and record the REAL
    per-neighbor wire bytes each mode moves. The compact leg's claim: it
    transfers <= capacity/n_params of the dense value lanes (plus the
    L-byte fire vector and, on int8, the L-scale vector) and is no slower
    than the masked exchange it replaces. Fire pattern: leaves admitted in
    leaf order until ~30%% of the payload bytes are lit; capacity sized
    like the train-loop autotuner (observed fired peak, 1.25x headroom,
    floor = largest leaf). On the small reference models one dense kernel
    dominates the parameter count, so the floor pins capacity near
    n_params — the ResNet geometry (86 leaves, largest ~21% of the model)
    is where the byte ratio shows."""
    import os

    from eventgrad_tpu.models import MLP, ResNet18
    from eventgrad_tpu.parallel import collectives
    from eventgrad_tpu.parallel.spmd import spmd, stack_for_ranks
    from eventgrad_tpu.parallel.topology import Ring

    topo = Ring(4)
    results = []

    def _fire_bits(sizes, frac):
        total = sum(sizes)
        fired, acc = [], 0
        for s in sizes:
            take = acc + s <= frac * total
            fired.append(take)
            if take:
                acc += s
        if not any(fired):  # a degenerate tree: light the first leaf
            fired[0] = True
        return fired, acc

    def _exchange_case(name, params):
        leaves, treedef = jax.tree.flatten(params)
        sizes = [int(l.size) for l in leaves]
        n = sum(sizes)
        fired_bits, fired_elems = _fire_bits(sizes, 0.30)
        fire = treedef.unflatten([jnp.asarray(b) for b in fired_bits])
        fire_st = stack_for_ranks(fire, topo)  # per-rank bits for the lift
        cap = collectives.choose_capacity(
            n, fired_elems, collectives.compact_capacity_floor(sizes)
        )
        stacked = stack_for_ranks(params, topo)
        last = jax.tree.map(jnp.zeros_like, stacked)
        for wire in (None, "bf16", "int8"):
            wire_name = {None: "f32", "bf16": "bf16", "int8": "int8"}[wire]
            dense = jax.jit(spmd(
                lambda t: collectives.neighbor_vals(t, topo, wire), topo))
            masked = jax.jit(spmd(
                lambda p, f, l: collectives.masked_neighbor_vals(
                    p, f, (l, l), topo, wire), topo))
            compact = jax.jit(spmd(
                lambda p, f, l: collectives.compact_neighbor_vals(
                    p, f, (l, l), topo, cap, wire), topo))
            tm = dict(iters=2, repeats=2) if n > 1e6 else dict(iters=10,
                                                              repeats=3)
            ms = {
                "dense": _time(dense, stacked, **tm),
                "masked": _time(masked, stacked, fire_st, last, **tm),
                "compact": _time(compact, stacked, fire_st, last, **tm),
            }
            for mode, t in ms.items():
                real = collectives.wire_real_bytes_per_neighbor(
                    n, len(sizes), wire,
                    compact_capacity=cap if mode == "compact" else None,
                    fire_bits=mode != "dense",
                )
                rec = {
                    "kernel": "gossip_exchange", "config": name,
                    "mode": mode, "wire": wire_name, "ms": round(t, 3),
                    "wire_bytes_per_neighbor": real,
                    "n_params": n, "n_leaves": len(sizes),
                    "fired_elems": fired_elems, "capacity": cap,
                }
                _emit(rec)
                results.append(rec)
        return ms

    key = jax.random.PRNGKey(0)
    mlp = MLP().init(key, jnp.zeros((1, 28, 28, 1)))["params"]
    _exchange_case("mlp", mlp)
    resnet = ResNet18(dtype=jnp.float32).init(
        key, jnp.zeros((1, 32, 32, 3)))["params"]
    _exchange_case("resnet18", resnet)

    # whole-train-step leg: compact must be no slower than the masked step
    # it replaces (it strictly removes work when capacity < n_params: no
    # full-model mask materialization, a [C]-sized shift instead of [N])
    import optax

    from eventgrad_tpu.data.datasets import synthetic_dataset
    from eventgrad_tpu.models import MODEL_REGISTRY
    from eventgrad_tpu.parallel.events import EventConfig
    from eventgrad_tpu.train.state import init_train_state
    from eventgrad_tpu.train.steps import make_train_step

    for model_name, in_shape, batch in (("cnn2", (28, 28, 1), 64),
                                        ("resnet18", (32, 32, 3), 4)):
        model = MODEL_REGISTRY[model_name]()
        tx = optax.sgd(0.05)
        cfg = EventConfig(adaptive=True, horizon=1.05, warmup_passes=2,
                          max_silence=50)
        state = init_train_state(model, in_shape, tx, topo, "eventgrad", cfg)
        leaves = jax.tree.leaves(state.params)
        sizes = [int(np.prod(l.shape[1:])) or 1 for l in leaves]
        n = sum(sizes)
        fired_bits, fired_elems = _fire_bits(sizes, 0.30)
        cap = collectives.choose_capacity(
            n, max(fired_elems, 1),
            collectives.compact_capacity_floor(sizes))
        x, y = synthetic_dataset(batch * topo.n_ranks, in_shape, seed=3)
        xb = jnp.asarray(x.reshape((topo.n_ranks, batch) + in_shape))
        yb = jnp.asarray(y.reshape((topo.n_ranks, batch)))
        step_ms = {}
        for mode in ("dense", "compact"):
            step = make_train_step(
                model, tx, topo, "eventgrad", event_cfg=cfg,
                gossip_wire=mode,
                compact_capacity=cap if mode == "compact" else None,
            )
            lifted = jax.jit(spmd(step, topo))
            st = jax.tree.map(lambda v: v, state)  # fresh copy per mode
            ms = _time(lambda s, b: lifted(s, b), st, (xb, yb),
                       iters=2, repeats=2)
            step_ms[mode] = ms
            rec = {"kernel": "gossip_step", "config": model_name,
                   "mode": "masked" if mode == "dense" else "compact",
                   "ms": round(ms, 3), "n_params": n, "capacity": cap}
            _emit(rec)
            results.append(rec)
        _emit({"kernel": "gossip_step", "config": f"{model_name}:ratio",
               "compact_over_masked": round(
                   step_ms["compact"] / step_ms["dense"], 3)})

    platform = jax.devices()[0].platform
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "artifacts", f"gossip_wire_{platform}.json")
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"platform": platform,
                   "device_kind": jax.devices()[0].device_kind,
                   "entries": results}, f, indent=1)
        f.write("\n")
    os.replace(tmp, path)
    _emit({"artifact": path, "n_entries": len(results)})


def bench_arena():
    """Flat-arena event-engine ops vs their XLA/legacy twins.

    * event_propose_pack — the fused trigger->gate->pack sender pass vs
      the legacy chain (flatten -> propose -> capacity_gate ->
      ravel -> _compact_pack), MLP and ResNet18 geometries; max_err
      covers the packed wire buffer and the gated fire bits (expect 0).
    * masked_wire — the Pallas masked-wire builder kernel vs the fused
      jnp mask the flat exchange inlines (interpret mode off-TPU).
    * fused_mix_commit — the Pallas commit+mix+SGD kernel vs
      mix_commit_reference (interpret mode off-TPU).

    On TPU the measured speedups are written to
    eventgrad_tpu/ops/arena_tuning.json — the dispatch table
    ops/arena_tuning.py consults (kernels only run where they won)."""
    import os

    from eventgrad_tpu.models import MLP, ResNet18
    from eventgrad_tpu.ops import arena_update, event_engine
    from eventgrad_tpu.parallel import arena, collectives
    from eventgrad_tpu.parallel.events import (
        EventConfig, EventState, capacity_gate, propose,
    )
    from eventgrad_tpu.parallel.topology import Ring
    from jax.flatten_util import ravel_pytree

    topo = Ring(4)
    cfg = EventConfig(adaptive=True, horizon=1.05, warmup_passes=1,
                      max_silence=50)
    on_tpu = jax.devices()[0].platform == "tpu"
    speedups = {}

    key = jax.random.PRNGKey(0)
    geoms = {
        "mlp": MLP().init(key, jnp.zeros((1, 28, 28, 1)))["params"],
        "resnet18": ResNet18(dtype=jnp.float32).init(
            key, jnp.zeros((1, 32, 32, 3)))["params"],
    }
    for name, params in geoms.items():
        spec = arena.arena_spec(params)
        state = EventState.init(params, topo, cfg)
        cap = collectives.choose_capacity(
            spec.n_total, 0.3 * spec.n_total,
            collectives.compact_capacity_floor(spec.sizes),
        )
        pn = jnp.int32(60)

        def chain(p, s):
            prop = propose(p, s, pn, cfg)
            pri = prop.iter_diff >= cfg.max_silence
            sizes, starts, _n = collectives._leaf_meta(p)
            fire = capacity_gate(prop.fire_vec, sizes, cap, priority=pri)
            flat, _ = ravel_pytree(p)
            packed, leaf_id = collectives._compact_pack(
                flat, fire, sizes, starts, cap
            )
            return fire, packed

        def fused(p, s):
            _prop, fire, packed, _lid = event_engine.event_propose_pack(
                p, s, pn, cfg, spec, capacity=cap
            )
            return fire, packed

        jc, jf = jax.jit(chain), jax.jit(fused)
        fc, pc = jc(params, state)
        ff, pf = jf(params, state)
        err = max(
            float(jnp.max(jnp.abs(pc - pf))),
            float(jnp.max(jnp.abs(fc.astype(jnp.int8)
                                  - ff.astype(jnp.int8)))),
        )
        assert err == 0.0, f"event_propose_pack diverges from chain: {err}"
        ms_f, ms_c = _time(jf, params, state), _time(jc, params, state)
        _emit({
            "kernel": "event_propose_pack", "config": name,
            "pallas_ms": round(ms_f, 3), "xla_ms": round(ms_c, 3),
            "speedup": round(ms_c / ms_f, 2), "max_err": err,
            "capacity": cap, "n_params": spec.n_total,
        })

    # masked_wire kernel (the wire build of the masked flat exchange)
    params = geoms["resnet18"]
    spec = arena.arena_spec(params)
    flat, _ = ravel_pytree(params)
    seg = spec.seg_expand()
    fire_vec = jnp.arange(spec.n_leaves) % 3 != 0
    fire_exp = fire_vec[seg]
    kern = jax.jit(lambda f, e: event_engine.masked_wire(
        f, e, interpret=not on_tpu))
    ref = jax.jit(event_engine.masked_wire_reference)
    err = _max_err(kern(flat, fire_exp), ref(flat, fire_exp))
    assert err == 0.0, f"masked_wire diverges from reference: {err}"
    tm = dict(iters=3, repeats=3) if not on_tpu else {}
    ms_k = _time(kern, flat, fire_exp, **tm)
    ms_r = _time(ref, flat, fire_exp, **tm)
    speedups["masked_wire_speedup"] = round(ms_r / ms_k, 3)
    _emit({
        "kernel": "masked_wire", "config": "resnet18",
        "pallas_ms": round(ms_k, 3), "xla_ms": round(ms_r, 3),
        "speedup": speedups["masked_wire_speedup"], "max_err": err,
        "interpret": not on_tpu,
    })

    # fused_mix_commit kernel vs jnp twin at a lane-aligned size
    n = 1 << 20
    k2 = jax.random.PRNGKey(2)
    p, g, t, c0, c1, l0, l1 = (
        jax.random.normal(jax.random.fold_in(k2, i), (n,)) for i in range(7)
    )
    k0 = jax.random.uniform(jax.random.fold_in(k2, 8), (n,)) > 0.5
    k1 = jax.random.uniform(jax.random.fold_in(k2, 9), (n,)) > 0.3
    kern = jax.jit(lambda *a: arena_update.fused_mix_commit(
        *a, 0.01, 0.9, 1 / 3, interpret=not on_tpu))
    ref = jax.jit(lambda *a: arena_update.mix_commit_reference(
        *a, 0.01, 0.9, 1 / 3))
    ok = kern(p, (c0, c1), (k0, k1), (l0, l1), g, t)
    orf = ref(p, (c0, c1), (k0, k1), (l0, l1), g, t)
    err = max(
        _max_err(a, b)
        for a, b in zip(jax.tree.leaves(ok), jax.tree.leaves(orf))
    )
    assert err == 0.0, f"fused_mix_commit diverges from reference: {err}"
    tm = dict(iters=3, repeats=3) if not on_tpu else {}
    ms_k = _time(kern, p, (c0, c1), (k0, k1), (l0, l1), g, t, **tm)
    ms_r = _time(ref, p, (c0, c1), (k0, k1), (l0, l1), g, t, **tm)
    speedups["mix_commit_speedup"] = round(ms_r / ms_k, 3)
    _emit({
        "kernel": "fused_mix_commit", "config": f"{n/1e6:.1f}M x2 neighbors",
        "pallas_ms": round(ms_k, 3), "xla_ms": round(ms_r, 3),
        "speedup": speedups["mix_commit_speedup"], "max_err": err,
        "interpret": not on_tpu,
    })

    if on_tpu:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "eventgrad_tpu", "ops", "arena_tuning.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"platform": jax.devices()[0].device_kind,
                       **speedups}, f, indent=1)
            f.write("\n")
        os.replace(tmp, path)
        _emit({"tuned": path, **speedups})
    else:
        _emit({"tuned": None,
               "note": "non-TPU platform: arena_tuning.json not written "
                       "(interpret-mode timings are not dispatch evidence)"})


def bench_bucketed(k_buckets=(2, 4, 8)):
    """The bucketed fused tail vs the monolithic fused tail (ISSUE 10
    satellite: the bucketed KERNEL path must earn its dispatch).

    The bucketed gossip schedule with fused_sgd launches ONE
    fused_mix_commit per bucket instead of one for the whole arena —
    the many-launch regime the fused family measured as a loss on
    trees. This leg proves the per-bucket decomposition BIT-EQUAL to
    the monolithic call on the LeNetCifar geometry, times both, and
    merges the measured ratios into eventgrad_tpu/ops/arena_tuning.json
    — the entries ops/arena_tuning.bucketed_tail_ok() gates on. Two
    entry shapes land there: a per-platform per-K dict
    (`bucketed_tail_speedup_by_platform`, written on EVERY platform —
    on CPU both sides time the jnp reference twins, which is exactly
    the dispatch decision CPU runs face, so the CPU entry is real
    dispatch evidence and stops the silent demotion there) and the
    legacy worst-K scalar (`bucketed_tail_speedup`, TPU only). No
    entry for the active platform -> the step falls back to the
    monolithic fused path instead of guessing."""
    import os

    from eventgrad_tpu.models import LeNetCifar
    from eventgrad_tpu.ops import arena_update
    from eventgrad_tpu.parallel import arena

    on_tpu = jax.default_backend() == "tpu"
    params = LeNetCifar().init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3))
    )["params"]
    spec = arena.arena_spec(params)
    flat = spec.ravel(params)
    n = spec.n_total
    k0 = jax.random.PRNGKey(7)
    g, t, c0, c1, l0, l1 = (
        jax.random.normal(jax.random.fold_in(k0, i), (n,)) for i in range(6)
    )
    keep0 = jax.random.uniform(jax.random.fold_in(k0, 8), (n,)) > 0.5
    keep1 = jax.random.uniform(jax.random.fold_in(k0, 9), (n,)) > 0.3
    # on CPU both sides run the jnp reference twin (interpret-mode
    # Pallas timings are not dispatch evidence); on TPU both run the
    # kernel — the ratio isolates the K-launch split either way
    tail = (
        (lambda *a, **kw: arena_update.fused_mix_commit(
            *a, interpret=False, **kw))
        if on_tpu else arena_update.mix_commit_reference
    )

    def mono(p, c0, c1, k0_, k1_, l0_, l1_, g_, t_):
        return tail(p, (c0, c1), (k0_, k1_), (l0_, l1_), g_, t_,
                    0.01, 0.9, 1 / 3)

    jmono = jax.jit(mono)
    ref = jmono(flat, c0, c1, keep0, keep1, l0, l1, g, t)
    jax.block_until_ready(ref)
    speed = {}
    for K in k_buckets:
        buckets = spec.buckets(K)

        def bucketed(p, c0, c1, k0_, k1_, l0_, l1_, g_, t_, _bs=buckets):
            outs = []
            for b in _bs:
                sl = slice(b.start, b.start + b.size)
                outs.append(tail(
                    p[sl], (c0[sl], c1[sl]), (k0_[sl], k1_[sl]),
                    (l0_[sl], l1_[sl]), g_[sl], t_[sl], 0.01, 0.9, 1 / 3,
                ))
            return outs

        jb = jax.jit(bucketed)
        out = jb(flat, c0, c1, keep0, keep1, l0, l1, g, t)
        jax.block_until_ready(out)
        # bit-equality: the tail is elementwise per position, so the
        # per-bucket split must reproduce the monolithic result exactly
        for field in range(3):
            mono_f = jax.tree.leaves(ref[field])
            buck_f = [jax.tree.leaves(o[field]) for o in out]
            cat = [
                np.concatenate([np.asarray(x).reshape(-1) for x in grp])
                for grp in zip(*buck_f)
            ] if isinstance(ref[field], tuple) else [np.concatenate(
                [np.asarray(o[field]) for o in out]
            )]
            for m, b_ in zip(
                [np.asarray(x).reshape(-1) for x in mono_f]
                if isinstance(ref[field], tuple) else
                [np.asarray(ref[field])],
                cat,
            ):
                assert np.array_equal(m, b_), "bucketed tail diverges"
        tm = dict(iters=3, repeats=3) if not on_tpu else {}
        ms_m = _time(jmono, flat, c0, c1, keep0, keep1, l0, l1, g, t, **tm)
        ms_b = _time(jb, flat, c0, c1, keep0, keep1, l0, l1, g, t, **tm)
        speed[K] = round(ms_m / ms_b, 3)
        _emit({
            "kernel": "bucketed_mix_commit", "config": f"LeNetCifar K={K}",
            "bucketed_ms": round(ms_b, 3), "monolithic_ms": round(ms_m, 3),
            "speedup": speed[K], "max_err": 0.0, "n_params": n,
            "interpret_twin": not on_tpu,
        })

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "eventgrad_tpu", "ops", "arena_tuning.json")
    try:
        with open(path) as f:
            table = json.load(f)
    except (OSError, json.JSONDecodeError):
        table = {"platform": jax.devices()[0].device_kind}
    # per-platform per-K entries, written on EVERY platform: the gate
    # (ops/arena_tuning.bucketed_tail_ok) decides per configured K, so
    # a measured-losing K demotes while a measured-winning K runs
    by_plat = table.setdefault("bucketed_tail_speedup_by_platform", {})
    by_plat[jax.default_backend()] = {str(K): v for K, v in speed.items()}
    if on_tpu:
        # legacy worst-K scalar: the fallback older tables gate on
        table["bucketed_tail_speedup"] = min(speed.values())
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(table, f, indent=1)
        f.write("\n")
    os.replace(tmp, path)
    _emit({"tuned": path,
           "platform": jax.default_backend(),
           "bucketed_tail_speedup_by_k": by_plat[jax.default_backend()]})


def tune_flash(seqs=(512, 1024, 2048, 4096), blocks=(128, 256, 512)):
    """Per-shape block sweep -> eventgrad_tpu/ops/flash_tuning.json."""
    import os

    from eventgrad_tpu.ops import flash_attention, flash_attention_reference

    b, h, d = 4, 8, 64
    entries = []
    for t in seqs:
        key = jax.random.PRNGKey(0)
        q, k, v = (
            jax.random.normal(jax.random.fold_in(key, i), (b, t, h, d), jnp.bfloat16)
            for i in range(3)
        )
        # lighter timing than the headline grid (iters 10 x 3 bursts):
        # the sweep is 24+ compile-and-measure configs and must fit the
        # watcher's 1800 s rung deadline; winners get re-measured at full
        # depth by the kernels grid that runs after tuning
        tmr = dict(iters=10, repeats=3)
        ref_f = jax.jit(lambda q, k, v: flash_attention_reference(q, k, v, True))
        ref_g = jax.jit(jax.grad(lambda q: jnp.sum(
            flash_attention_reference(q, k, v, True).astype(jnp.float32) ** 2)))
        xla_f, xla_g = _time(ref_f, q, k, v, **tmr), _time(ref_g, q, **tmr)
        for mode, xla_ms in (("fwd", xla_f), ("fwd_bwd", xla_g)):
            best = {"t": t, "mode": mode, "pallas": False, "block": blocks[0],
                    "pallas_ms": None, "xla_ms": round(xla_ms, 3)}
            for blk in blocks:
                if blk > t:
                    continue
                try:
                    if mode == "fwd":
                        fn = jax.jit(lambda q, k, v, _b=blk: flash_attention(
                            q, k, v, True, block=_b))
                        ms = _time(fn, q, k, v, **tmr)
                    else:
                        fn = jax.jit(jax.grad(lambda q, _b=blk: jnp.sum(
                            flash_attention(q, k, v, True, block=_b)
                            .astype(jnp.float32) ** 2)))
                        ms = _time(fn, q, **tmr)
                except Exception as e:  # a block config may not compile
                    _emit({"kernel": f"flash_{mode}", "config": f"T{t}b{blk}",
                           "error": repr(e)[:200]})
                    continue
                _emit({"kernel": f"flash_{mode}", "config": f"T{t}b{blk}",
                       "pallas_ms": round(ms, 3), "xla_ms": round(xla_ms, 3),
                       "speedup": round(xla_ms / ms, 2)})
                if best["pallas_ms"] is None or ms < best["pallas_ms"]:
                    best.update(pallas_ms=round(ms, 3), block=blk)
            # the kernel must measurably beat XLA to stay on this shape
            best["pallas"] = bool(
                best["pallas_ms"] is not None and best["pallas_ms"] < xla_ms
            )
            entries.append(best)
            _emit({"kernel": f"flash_{mode}", "config": f"T{t}:winner",
                   **{k_: best[k_] for k_ in ("pallas", "block", "pallas_ms",
                                              "xla_ms")}})
    # sanity pass (ADVICE r4: a broken xla baseline — 0.017 ms at T=512,
    # ~200x below the same-shape full-depth grid — was committed into the
    # dispatch table): attention cost grows ~t^2, so every xla_ms should
    # sit near one shared t^2-normalized cost. The r4 rule trusted the
    # next-LARGER t as its back-projection anchor, so a broken-low
    # largest-t entry escaped detection AND corrupted the check for its
    # smaller neighbor (ADVICE r5 #3); the MEDIAN normalized cost across
    # the sweep is anchor-free — any single broken entry, including the
    # largest t, lands >8x below it and gets imputed from the healthy
    # majority.
    def _median(vals):
        s = sorted(vals)
        mid = len(s) // 2
        return s[mid] if len(s) % 2 else 0.5 * (s[mid - 1] + s[mid])

    for mode in ("fwd", "fwd_bwd"):
        es = sorted((e for e in entries if e["mode"] == mode),
                    key=lambda e: e["t"])
        if len(es) < 2:
            continue  # a single entry has nothing to cross-check against
        med = _median([e["xla_ms"] / e["t"] ** 2 for e in es])
        broken = [e for e in es if e["xla_ms"] < med * e["t"] ** 2 / 8.0]
        if not broken:
            continue
        healthy = [
            e["xla_ms"] / e["t"] ** 2 for e in es if e not in broken
        ]
        impute_cost = _median(healthy) if healthy else med
        for a in broken:
            a["xla_ms_broken"] = a["xla_ms"]
            a["xla_ms"] = round(impute_cost * a["t"] ** 2, 3)
            a["xla_ms_imputed"] = True
            a["pallas"] = bool(
                a["pallas_ms"] is not None and a["pallas_ms"] < a["xla_ms"]
            )
            _emit({"kernel": f"flash_{mode}", "config": f"T{a['t']}:sanity",
                   "xla_ms_broken": a["xla_ms_broken"],
                   "xla_ms_imputed": a["xla_ms"]})
    if jax.devices()[0].platform == "tpu":
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "eventgrad_tpu", "ops", "flash_tuning.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            # swept=true marks a real on-chip block sweep — the watcher
            # uses it to tell this apart from a hand-seeded table
            json.dump({"platform": jax.devices()[0].device_kind,
                       "swept": True, "entries": entries}, f, indent=1)
            f.write("\n")
        os.replace(tmp, path)
        _emit({"tuned": path, "n_entries": len(entries)})
    else:
        _emit({"tuned": None,
               "note": "non-TPU platform: table not written"})


if __name__ == "__main__":
    args = sys.argv[1:]
    which = args[0] if args and not args[0].startswith("--") else "all"
    if which not in ("attn", "fused", "gossip", "arena", "bucketed",
                     "all", "tune"):
        raise SystemExit(
            f"unknown selector {which!r}: attn | fused | gossip | arena | "
            "bucketed | all | tune"
        )
    seqs = (512, 1024, 2048, 4096)
    for i, a in enumerate(args):
        if a in ("--seqs", "--out") and i + 1 >= len(args):
            raise SystemExit(f"{a} needs a value (see module docstring)")
        if a == "--seqs":
            seqs = tuple(int(s) for s in args[i + 1].split(","))
        if a == "--out":
            _OUT_PATH = args[i + 1]
    _emit({"platform": jax.devices()[0].platform,
           "device_kind": jax.devices()[0].device_kind})
    if which == "tune":
        tune_flash(seqs)
    if which in ("attn", "all"):
        bench_attention(seqs)
    if which in ("fused", "all"):
        bench_fused_update()
    if which in ("arena", "all"):
        bench_arena()
    if which in ("bucketed", "all"):
        bench_bucketed()
    if which in ("gossip", "all"):
        bench_gossip_wire()
