"""Messages-saved trajectory at reference-scale pass counts (VERDICT item 4
evidence).

One eventgrad leg per headline config at horizon 1.0 / warmup 30
(the reference's sample adaptive run, dmnist/event/README.md): MNIST CNN-2
at the full 1168-pass op-point (event.cpp:255: 10 epochs x ~117 steps) and
CIFAR tiny-ResNet at 256 passes. Prints a JSON line per config with the
final msgs-saved-% and its trajectory (`trail`) — savings climb as training
converges because parameter-norm drift shrinks, so they must be judged at
the reference pass counts, not short smoke tiers.

The op-points are tools/tune_horizon.py's `run_point` — one definition, so
the sweep artifacts and these curves measure the same config (this script
just runs longer, single-leg, with a trajectory).

Round-2 CPU result committed as artifacts/savings_curve_r2_cpu.jsonl:
MNIST 66.2% @1168 passes (rising; ~70% claim within reach — and
artifacts/mnist_parity_r2_cpu.json adds the D-PSGD legs: acc gap −0.58pp),
CIFAR 59.3% @1024 passes rising ~0.4pp/128 passes, crossing the ~60%
target within the 3904-pass flagship scale.

Usage: JAX_PLATFORMS=cpu python tools/savings_curve.py"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from tune_horizon import run_point  # noqa: E402  (shares the op-points)

if __name__ == "__main__":
    # MNIST at the reference op-point scale: 292 epochs x 4 steps = 1168
    run_point("mnist", 1.0, warmup=30, epochs=292, dpsgd_leg=False,
              trail_every=40)
    # CIFAR, 64 epochs x 16 steps = 1024 passes
    run_point("cifar", 1.0, warmup=30, epochs=64, dpsgd_leg=False,
              trail_every=4)
