"""Deadlined subprocesses + accelerator liveness probing.

The TPU tunnel can wedge a blocked device op forever — no Python-level
interrupt works, and a child stuck in an uninterruptible device op can
even survive SIGKILL-then-reap. A supervising parent with a hard wall
deadline is the only reliable watchdog. This is the single home for that
logic: bench.py's supervisor and tools/tpu_watch.py both ride these two
helpers, so "tunnel alive" means exactly one thing repo-wide (an
*executed* jit — a wedged tunnel enumerates devices fine but blocks on
first use).
"""

from __future__ import annotations

import os
import subprocess
import sys


def run_deadlined(cmd, env, timeout_s, cwd=None, capture_stderr=False):
    """subprocess with a hard wall deadline that cannot hang the parent.

    subprocess.run(timeout=...)'s TimeoutExpired path waits forever on a
    child stuck in an uninterruptible device op: kill, give it a short
    grace to be reaped (salvaging anything already printed — a child that
    completed its measurement and then wedged in device teardown is a
    result), then abandon it unreaped.

    Returns (stdout_or_None, timed_out, returncode_or_None).
    """
    try:
        proc = subprocess.Popen(
            cmd, env=env, stdout=subprocess.PIPE, text=True,
            stderr=subprocess.STDOUT if capture_stderr else None,
            cwd=cwd or os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))),
        )
    except OSError:
        return None, False, None
    try:
        out, _ = proc.communicate(timeout=timeout_s)
        return out, False, proc.returncode
    except subprocess.TimeoutExpired:
        proc.kill()
        try:
            out, _ = proc.communicate(timeout=10)
            return out, True, None
        except (subprocess.TimeoutExpired, OSError):
            pass  # unkillable child; abandon without reaping
        return None, True, None
    except OSError:
        # pipe read failed (e.g. EIO from a dying child) — callers'
        # contract is a result tuple, never an exception
        return None, False, None


# Staged probe child: every phase is bracketed by flushed EG_STAGE
# markers so that when the parent kills a wedged child, the salvaged
# partial stdout pinpoints WHERE the tunnel wedged (import vs device
# enumeration vs executed jit) — round-3's probe log could only say
# "stalled", which the round-3 verdict flagged as insufficient diagnosis.
_PROBE_CODE = (
    "print('EG_STAGE spawn', flush=True)\n"
    "import os, jax, jax.numpy as jnp\n"
    "from eventgrad_tpu.utils import compile_cache\n"
    "compile_cache.honor_cpu_pin()\n"
    "print('EG_STAGE import_ok', jax.__version__, flush=True)\n"
    "print('EG_STAGE enum_start', flush=True)\n"
    "ds = jax.devices()\n"
    "print('EG_STAGE enum_ok', ds[0].platform, len(ds), flush=True)\n"
    "print('EG_STAGE jit_start', flush=True)\n"
    "jax.block_until_ready(jax.jit(lambda a: a @ a)(jnp.ones((256, 256))))\n"
    "print('EG_STAGE jit_ok', flush=True)\n"
    "d = ds[0]\n"
    "{tpu_assert}"
    "print('EG_PROBE_OK', d.platform, d.device_kind, flush=True)\n"
)


def probe_device_diag(env, timeout_s, require_tpu=False):
    """Diagnostic liveness probe. Returns a dict:

      verdict   'ok' | 'stalled' | 'crashed'
      platform  jax platform string or None
      stage     last marker the child reached ('spawn', 'import_ok',
                'enum_start', 'enum_ok', 'jit_start', 'jit_ok', or
                'probe_ok' on full success) — for a stalled child this
                names the phase the tunnel wedged in; None if no marker
                was salvaged
      tail      last chunk of combined stdout+stderr (exception text for
                crashes, plugin chatter for stalls)

    'ok' iff the backend completes an *executed* jit AND the child exits
    within the deadline — a child that prints its success line but then
    wedges in device teardown is still 'stalled' (same rule as the old
    probe: a tunnel that cannot tear down cleanly will wedge the next
    real workload too). With require_tpu, a healthy non-TPU backend
    counts as 'crashed'."""
    code = _PROBE_CODE.format(
        tpu_assert=("assert d.platform == 'tpu', d.platform\n"
                    if require_tpu else "")
    )
    out, timed_out, rc = run_deadlined(
        [sys.executable, "-c", code], env, timeout_s, capture_stderr=True
    )
    # Markers are matched with `in`, not startswith: the C++ plugin
    # writes unbuffered chunks to the same merged pipe and can prepend a
    # partial line to a marker.
    stage, platform = None, None
    for line in (out or "").splitlines():
        if "EG_STAGE" in line:
            parts = line[line.index("EG_STAGE"):].split()
            stage = parts[1] if len(parts) > 1 else stage
            if stage == "enum_ok" and len(parts) > 2:
                platform = parts[2]
        elif "EG_PROBE_OK" in line and not timed_out:
            parts = line[line.index("EG_PROBE_OK"):].split()
            return {"verdict": "ok", "stage": "probe_ok",
                    "platform": parts[1] if len(parts) > 1 else None,
                    "tail": None, "rc": rc}
    verdict = "stalled" if timed_out else "crashed"
    return {"verdict": verdict, "stage": stage, "platform": platform,
            "tail": (out or "")[-1500:] or None, "rc": rc}


def probe_device(env, timeout_s, require_tpu=False):
    """(verdict, platform) compatibility wrapper over probe_device_diag
    — bench.py's supervisor only needs the binary liveness answer. The
    child's stderr is merged into the diag tail now, so on failure the
    tail is re-emitted on this process's stderr to keep the probe's
    diagnostics visible in the caller's own logs."""
    d = probe_device_diag(env, timeout_s, require_tpu=require_tpu)
    if d["verdict"] != "ok" and d.get("tail"):
        print("[probe %s @%s] %s" % (d["verdict"], d.get("stage"),
                                     d["tail"][-400:]),
              file=sys.stderr)
    return d["verdict"], d["platform"]
