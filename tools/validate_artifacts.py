"""Committed-artifact validation: every JSON the repo ships must parse
and match its family's schema.

The repo's evidence chain is its committed artifacts — BENCH/MULTICHIP
driver records, `artifacts/*.json(l)` measurement captures, the obs
run-report. A malformed artifact (truncated write, hand-edit typo,
schema drift in a tool) silently rots that chain; this tool makes it a
tier-1 test failure instead (tests/test_artifacts.py runs
`validate_repo` on every suite run).

Validation is a dependency-free subset of JSON Schema (the container has
no `jsonschema` package and the repo adds no deps): type / required /
properties / items / enum / minimum / maximum / minItems. Schemas are
deliberately PERMISSIVE — they pin the fields tools and docs rely on
(readers tolerate unknown keys, mirroring obs.schema's compatibility
rule), not every field ever written.

Usage: python tools/validate_artifacts.py [--root PATH]
Exit 0 = all checked files valid; 1 = violations (listed on stderr).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional

_TYPES = {
    "object": dict,
    "array": list,
    "string": str,
    "boolean": bool,
    "null": type(None),
}


def _type_ok(value: Any, t: str) -> bool:
    if t == "number":
        return isinstance(value, (int, float)) and not isinstance(value, bool)
    if t == "integer":
        return isinstance(value, int) and not isinstance(value, bool)
    return isinstance(value, _TYPES[t])


def validate(instance: Any, schema: Dict[str, Any], path: str = "$") -> List[str]:
    """Errors (empty = valid) of `instance` against the schema subset."""
    errs: List[str] = []
    t = schema.get("type")
    if t is not None:
        types = t if isinstance(t, list) else [t]
        if not any(_type_ok(instance, x) for x in types):
            return [
                f"{path}: expected type {'|'.join(types)}, got "
                f"{type(instance).__name__}"
            ]
    if "enum" in schema and instance not in schema["enum"]:
        errs.append(f"{path}: {instance!r} not in enum {schema['enum']}")
    if isinstance(instance, (int, float)) and not isinstance(instance, bool):
        if "minimum" in schema and instance < schema["minimum"]:
            errs.append(f"{path}: {instance} < minimum {schema['minimum']}")
        if "maximum" in schema and instance > schema["maximum"]:
            errs.append(f"{path}: {instance} > maximum {schema['maximum']}")
    if isinstance(instance, dict):
        for req in schema.get("required", ()):
            if req not in instance:
                errs.append(f"{path}: missing required key {req!r}")
        for key, sub in schema.get("properties", {}).items():
            if key in instance:
                errs.extend(validate(instance[key], sub, f"{path}.{key}"))
    if isinstance(instance, list):
        if "minItems" in schema and len(instance) < schema["minItems"]:
            errs.append(
                f"{path}: {len(instance)} items < minItems "
                f"{schema['minItems']}"
            )
        items = schema.get("items")
        if items is not None:
            for i, v in enumerate(instance):
                errs.extend(validate(v, items, f"{path}[{i}]"))
    return errs


# --- per-family schemas ----------------------------------------------------

_ANY_RECORD = {"type": ["object", "array"]}

BENCH_SCHEMA = {
    "type": "object",
    "required": ["n", "rc", "tail"],
    "properties": {
        "n": {"type": "integer", "minimum": 0},
        "rc": {"type": "integer"},
        "tail": {"type": "string"},
        "cmd": {"type": "string"},
    },
}

MULTICHIP_SCHEMA = {
    "type": "object",
    "required": ["n_devices", "ok", "rc", "skipped", "tail"],
    "properties": {
        "n_devices": {"type": "integer", "minimum": 0},
        "ok": {"type": "boolean"},
        "rc": {"type": "integer"},
        "skipped": {"type": "boolean"},
        "tail": {"type": "string"},
    },
}

_METRIC_LINE = {
    "type": "object",
    "required": ["metric", "value", "unit"],
    "properties": {
        "metric": {"type": "string"},
        "value": {"type": "number"},
        "unit": {"type": "string"},
    },
}

OBS_REPORT_SCHEMA = {
    "type": "object",
    "required": [
        "obs_schema", "epochs", "msgs_saved_pct_per_leaf",
        "capacity_utilization", "consensus_error",
    ],
    "properties": {
        "obs_schema": {"type": "integer", "minimum": 1},
        "epochs": {"type": "array", "minItems": 1,
                   "items": {"type": "integer"}},
        "msgs_saved_pct_per_leaf": {
            "type": ["object", "null"],
            "required": ["epochs", "leaves", "pct"],
            "properties": {
                "pct": {"type": "array",
                        "items": {"type": "array",
                                  "items": {"type": "number"}}},
            },
        },
        "capacity_utilization": {
            "type": ["object", "null"],
            "required": [
                "compact_capacity", "utilization_mean", "deferral_rate",
            ],
            "properties": {
                "compact_capacity": {"type": "integer", "minimum": 1},
                # compact-era only: the gate bounds per-pass fires by C
                "utilization_mean": {"type": ["number", "null"],
                                     "minimum": 0, "maximum": 1},
                "deferral_rate": {"type": "number", "minimum": 0,
                                  "maximum": 1},
            },
        },
        "consensus_error": {
            "type": ["object", "null"],
            "required": ["epochs", "max", "mean"],
        },
    },
}

OBS_OVERHEAD_SCHEMA = {
    "type": "object",
    "required": ["bench", "results", "overhead_pct_p50"],
    "properties": {
        "bench": {"enum": ["obs_overhead"]},
        "overhead_pct_p50": {"type": "number"},
        "results": {
            "type": "object",
            "required": ["obs_off", "obs_on"],
            "properties": {
                "obs_off": {"type": "object",
                            "required": ["step_ms_p50", "step_ms_mean"]},
                "obs_on": {"type": "object",
                           "required": ["step_ms_p50", "step_ms_mean"]},
            },
        },
    },
}

FLAGSHIP_SCHEMA = {
    "type": "object",
    "required": ["captured_at", "platform"],
    "properties": {
        "captured_at": {"type": "string"},
        "platform": {"type": "string"},
    },
}

_ARENA_LEG = {
    "type": "object",
    "required": ["dpsgd", "eventgrad", "step_overhead_ratio"],
    "properties": {
        "step_overhead_ratio": {"type": "number", "minimum": 0},
        "dpsgd": {"type": "object", "required": ["step_ms_min"]},
        "eventgrad": {"type": "object", "required": ["step_ms_min"]},
    },
}

ARENA_ABLATION_SCHEMA = {
    "type": "object",
    "required": [
        "bench", "op_point", "results", "overhead_ratio_before",
        "overhead_ratio_after", "platform",
    ],
    "properties": {
        "bench": {"enum": ["arena_ablation"]},
        "results": {
            "type": "object",
            "required": ["arena_off", "arena_on"],
            "properties": {
                "arena_off": _ARENA_LEG,
                "arena_on": _ARENA_LEG,
            },
        },
        "overhead_ratio_before": {"type": "number", "minimum": 0},
        # the flat-arena acceptance bound (ISSUE 4): the production-shape
        # EventGraD/D-PSGD step ratio with the arena on
        "overhead_ratio_after": {"type": "number", "minimum": 0,
                                 "maximum": 1.05},
        "platform": {"type": "string"},
    },
}

_BUBBLE_LEG = {
    "type": "object",
    "required": ["wall_s", "steps_s", "bubble_s", "host_bubble_frac"],
    "properties": {
        "wall_s": {"type": "number", "minimum": 0},
        "steps_s": {"type": "number", "minimum": 0},
        "bubble_s": {"type": "number", "minimum": 0},
        "host_bubble_frac": {"type": "number", "minimum": 0, "maximum": 1},
    },
}

BUCKETED_ABLATION_SCHEMA = {
    "type": "object",
    "required": [
        "bench", "op_point", "results", "overhead_ratio",
        "bitwise_state", "jaxpr_interleaved", "platform",
    ],
    "properties": {
        "bench": {"enum": ["bucketed_ablation"]},
        # the ISSUE 10 acceptance gates: the bucketed schedule's CPU
        # proxy costs <= 2% over the monolithic step (median paired
        # per-round, scanned steady state), trains BITWISE the same,
        # and the traced program actually interleaves exchange-side
        # ops between other buckets' update-side ops (the jaxpr gate,
        # analysis/walker.bucket_schedule) instead of forming one
        # prefix block
        "overhead_ratio": {"type": "number", "minimum": 0,
                           "maximum": 1.02},
        "bitwise_state": {"enum": [True]},
        "jaxpr_interleaved": {"enum": [True]},
        "results": {
            "type": "object",
            "required": ["k1", "k2", "k4", "k8"],
        },
    },
}

MESH_ABLATION_SCHEMA = {
    "type": "object",
    "required": [
        "bench", "platform", "n_devices", "op_point", "results",
        "step_overhead_ratio_mesh", "step_overhead_ratio_vmap",
        "mesh_vs_vmap_ratio", "bitwise_state", "audit", "scale64",
    ],
    "properties": {
        "bench": {"enum": ["mesh_ablation"]},
        "platform": {"type": "string"},
        # the real-mesh backend acceptance gates (ISSUE 14): the
        # EventGraD-vs-D-PSGD step ratio measured with REAL collectives
        # (one rank per device, actual ppermutes) stays in family with
        # the vmap proxy (<= 1.15 on the CPU capture; the r05 TPU
        # single-chip ratio was 1.09), the mesh lift costs bounded
        # overhead over the simulator at the same op-point, training is
        # BITWISE across the lifts, the mesh program audits clean at
        # production geometry with the seeded mesh oracle CAUGHT, and
        # the 64-rank scale leg's per-neighbor wire bytes match the
        # formula exactly
        "n_devices": {"type": "integer", "minimum": 8},
        "step_overhead_ratio_mesh": {"type": "number", "minimum": 0,
                                     "maximum": 1.15},
        "step_overhead_ratio_vmap": {"type": "number", "minimum": 0},
        "mesh_vs_vmap_ratio": {"type": "number", "minimum": 0,
                               "maximum": 1.3},
        "bitwise_state": {"enum": [True]},
        "results": {
            "type": "object",
            "required": ["vmap", "shard_map"],
        },
        "audit": {
            "type": "object",
            "required": [
                "lenet_clean", "resnet18_clean", "mesh_oracle_caught",
            ],
            "properties": {
                "lenet_clean": {"enum": [True]},
                "resnet18_clean": {"enum": [True]},
                "mesh_oracle_caught": {"enum": [True]},
            },
        },
        "scale64": {
            "type": "object",
            "required": ["n_ranks", "wire_bytes_exact", "offsets_ok"],
            "properties": {
                "n_ranks": {"type": "integer", "minimum": 64},
                "wire_bytes_exact": {"enum": [True]},
                "offsets_ok": {"enum": [True]},
            },
        },
    },
}

PIPELINE_BUBBLE_SCHEMA = {
    "type": "object",
    "required": [
        "bench", "platform", "results", "bubble_ratio", "bitwise_state",
    ],
    "properties": {
        "bench": {"enum": ["pipeline_bubble"]},
        "platform": {"type": "string"},
        "results": {
            "type": "object",
            "required": ["serial", "pipelined"],
            "properties": {
                "serial": _BUBBLE_LEG,
                "pipelined": _BUBBLE_LEG,
            },
        },
        # the dispatch-pipeline acceptance gate (ISSUE 5): pipelined
        # host-bubble fraction STRICTLY below the serial leg's
        "bubble_ratio": {"type": "number", "minimum": 0, "maximum": 0.999},
        # and bitwise-identical training state/metrics across the legs —
        # a perf artifact whose optimization changed training is invalid
        "bitwise_state": {"enum": [True]},
    },
}

SOAK_SCHEMA = {
    "type": "object",
    "required": [
        "bench", "platform", "op_point", "save_every", "n_transitions",
        "n_joins", "supervisor_restarts", "supervisor_escalations",
        "transitions", "active_ranks_verified", "recovery_ok",
        "final_acc_baseline",
        "final_acc_soak", "final_acc_gap_pt", "msgs_saved_pct",
        "replay_bitwise", "wall_s",
    ],
    "properties": {
        "bench": {"enum": ["soak"]},
        "platform": {"type": "string"},
        "save_every": {"type": "integer", "minimum": 1},
        # the elastic-membership acceptance gates (ISSUE 6): >= 6
        # scripted transitions, >= 2 of them joins, survived with ZERO
        # supervisor escalations, every recovery within one save
        # interval, replay from the logged schedule bitwise, and the
        # final accuracy within 0.5 pt of the transition-free baseline
        "n_transitions": {"type": "integer", "minimum": 6},
        "n_joins": {"type": "integer", "minimum": 2},
        "supervisor_restarts": {"type": "integer", "minimum": 1},
        "supervisor_escalations": {"enum": [0]},
        "transitions": {
            "type": "array",
            "minItems": 6,
            "items": {
                "type": "object",
                "required": ["kind", "epoch", "lost_epochs"],
                "properties": {
                    "kind": {"enum": ["join", "leave", "restart"]},
                    "epoch": {"type": "integer", "minimum": 1},
                    # epochs of recomputation the transition cost; the
                    # per-item bound vs save_every is recovery_ok below
                    "lost_epochs": {"type": "integer", "minimum": 0},
                },
            },
        },
        # per-epoch active_ranks tracked the logged schedule exactly —
        # the "transitions survived" proof
        "active_ranks_verified": {"enum": [True]},
        "recovery_ok": {"enum": [True]},
        "final_acc_gap_pt": {"type": "number", "minimum": 0,
                             "maximum": 0.5},
        "msgs_saved_pct": {"type": "number", "minimum": 0,
                           "maximum": 100},
        "replay_bitwise": {"enum": [True]},
        "wall_s": {"type": "number", "minimum": 0},
    },
}

INTEGRITY_SCHEMA = {
    "type": "object",
    "required": [
        "bench", "platform", "op_point", "schedule", "integrity",
        "injected_bitflips", "injected_nansteps", "wire_rejects",
        "quarantined_steps", "silent_acceptances", "rollbacks",
        "rollback", "final_acc_baseline", "final_acc_faulted",
        "acc_gap_pt", "replay_bitwise", "integrity_off_bitwise",
        "overhead", "wall_s",
    ],
    "properties": {
        "bench": {"enum": ["integrity"]},
        "platform": {"type": "string"},
        # the integrity-engine acceptance gates (ISSUE 7): a seeded
        # bitflip+nanstep schedule actually injected faults, EVERY one
        # was rejected at the wire / quarantined at the step / erased by
        # the rollback (ZERO silent acceptances), the divergence
        # sentinel tripped AT MOST one rollback, the post-rollback run
        # converged within 0.5 pt of the fault-free baseline, the whole
        # story replays bitwise from the seed, `--integrity off` is
        # bitwise today's traced step, and the in-step defenses cost
        # <= 2% p50 step time at the production-shape CPU proxy
        "injected_bitflips": {"type": "integer", "minimum": 1},
        "injected_nansteps": {"type": "integer", "minimum": 1},
        "wire_rejects": {"type": "integer", "minimum": 1},
        "quarantined_steps": {"type": "integer", "minimum": 1},
        "silent_acceptances": {"enum": [0]},
        "rollbacks": {"type": "integer", "minimum": 0, "maximum": 1},
        "rollback": {
            "type": "object",
            "required": ["reason", "tripped_epoch", "restored_epoch",
                         "hardened"],
            "properties": {
                "reason": {"type": "string"},
                "tripped_epoch": {"type": "integer", "minimum": 1},
                "restored_epoch": {"type": "integer", "minimum": 0},
                "hardened": {"enum": [True]},
            },
        },
        "acc_gap_pt": {"type": "number", "minimum": 0, "maximum": 0.5},
        "replay_bitwise": {"enum": [True]},
        "integrity_off_bitwise": {"enum": [True]},
        "overhead": {
            "type": "object",
            "required": ["step_ms_off_p50", "step_ms_on_p50",
                         "overhead_ratio_p50", "n_rounds"],
            "properties": {
                "step_ms_off_p50": {"type": "number", "minimum": 0},
                "step_ms_on_p50": {"type": "number", "minimum": 0},
                "overhead_ratio_p50": {"type": "number",
                                       "maximum": 1.02},
                "n_rounds": {"type": "integer", "minimum": 3},
            },
        },
        "wall_s": {"type": "number", "minimum": 0},
    },
}

_CRASH_CELL = {
    "type": "object",
    "required": [
        "config", "site", "hit", "crashed", "resumed",
        "final_state_bitwise", "history_bitwise", "lost_epochs",
        "crash_exit",
    ],
    "properties": {
        "config": {"type": "string"},
        "site": {"type": "string"},
        "hit": {"type": "integer", "minimum": 1},
        # every cell must have ACTUALLY crashed at the armed site (an
        # unfired site would read as "survived" vacuously), resumed,
        # and recovered bitwise — no exceptions, or the aggregate
        # unresumable/silent_data_loss pins below fail anyway
        "crashed": {"enum": [True]},
        "resumed": {"enum": [True]},
        "final_state_bitwise": {"enum": [True]},
        "history_bitwise": {"enum": [True]},
        "lost_epochs": {"type": "integer", "minimum": 0},
        "crash_exit": {"enum": [83]},
    },
}

_PREEMPT_CELL = {
    "type": "object",
    "required": [
        "kind", "exit", "marker", "final_state_bitwise",
        "history_bitwise", "lost_blocks",
    ],
    "properties": {
        "kind": {"enum": ["schedule", "signal"]},
        "exit": {"enum": [75]},
        "marker": {"enum": [True]},
        "final_state_bitwise": {"enum": [True]},
        "history_bitwise": {"enum": [True]},
        # the ISSUE 8 bound: graceful preemption loses AT MOST one
        # dispatch block of work (the boundary snapshot makes it 0)
        "lost_blocks": {"type": "integer", "minimum": 0, "maximum": 1},
    },
}

CRASH_MATRIX_SCHEMA = {
    "type": "object",
    "required": [
        "bench", "platform", "op_point", "configs", "exit_codes",
        "n_cells", "cells", "unresumable", "silent_data_loss",
        "recovery_ok", "preemption", "wall_s",
    ],
    "properties": {
        "bench": {"enum": ["crash_matrix"]},
        "platform": {"type": "string"},
        # the crash-consistency acceptance gates (ISSUE 8): every
        # registered crash site x configuration cell was killed at the
        # armed seam, resumed, and recovered the uninterrupted run's
        # final snapshot and history BITWISE — zero unresumable cells,
        # zero silent data loss, every recovery within one save
        # interval — and both graceful-preemption legs (scheduled
        # notice + real SIGTERM) exited PREEMPTED_EXIT with a marker
        # and lost at most one dispatch block
        "exit_codes": {
            "type": "object",
            "required": ["crashpoint", "preempted"],
            "properties": {
                "crashpoint": {"enum": [83]},
                "preempted": {"enum": [75]},
            },
        },
        "n_cells": {"type": "integer", "minimum": 12},
        "cells": {"type": "array", "minItems": 12, "items": _CRASH_CELL},
        "unresumable": {"enum": [0]},
        "silent_data_loss": {"enum": [0]},
        # every recomputation within the documented bound (one save
        # interval of snapshot age + one of pipeline run-ahead past a
        # killed async save)
        "recovery_bound_epochs": {"type": "integer", "minimum": 1},
        "recovery_ok": {"enum": [True]},
        "preemption": {
            "type": "object",
            "required": ["cells"],
            "properties": {
                "cells": {
                    "type": "array", "minItems": 2,
                    "items": _PREEMPT_CELL,
                },
            },
        },
        "wall_s": {"type": "number", "minimum": 0},
    },
}

_AUDIT_CELL = {
    "type": "object",
    "required": [
        "name", "algo", "model", "clean", "violations", "wire_match",
        "metric_match", "ravel_ok", "callbacks",
        "wire_bytes_per_neighbor_derived",
        "wire_bytes_per_neighbor_formula",
    ],
    "properties": {
        "name": {"type": "string"},
        "algo": {"enum": ["dpsgd", "eventgrad", "sp_eventgrad"]},
        # ISSUE 12: every cell names its audit geometry — the MLP
        # regression base or one of the PRODUCTION models the headline
        # numbers ship on (conv nets via rankflow's blocked-layout conv
        # rules, the transformer incl. flash via the declared-kernel
        # registry)
        "model": {"enum": ["mlp", "lenet", "resnet18", "transformer"]},
        "attn": {"enum": ["full", "flash"]},
        # every committed cell is CLEAN: zero rank-isolation
        # violations, the jaxpr-derived wire bytes equal the accounting
        # formula AND the executed step's sent_bytes_wire_real metric
        # exactly (in the metric's f32 carrier), the ravel budget
        # holds, no host callbacks
        "clean": {"enum": [True]},
        "violations": {"enum": [0]},
        "wire_match": {"enum": [True]},
        "metric_match": {"enum": [True]},
        "ravel_ok": {"enum": [True]},
        "callbacks": {"enum": [0]},
        "wire_bytes_per_neighbor_derived": {"type": "number", "minimum": 0},
        "wire_bytes_per_neighbor_formula": {"type": "number", "minimum": 0},
        # partitioned trigger policies (micro/hybrid) declare their
        # static partition offsets like fire-bit offsets; a committed
        # cell with a broken geometry (overlap/gap) is a violation
        "partitions_ok": {"enum": [True, None]},
    },
}

AUDIT_SCHEMA = {
    "type": "object",
    "required": [
        "bench", "platform", "op_point", "n_configs", "n_clean",
        "configs", "models", "n_oracles", "n_detected", "oracles",
        "lint_violations", "wall_s",
    ],
    "properties": {
        "bench": {"enum": ["audit"]},
        "platform": {"type": "string"},
        # the trace-auditor acceptance gates (ISSUE 9 + the ISSUE 12
        # full-geometry extension): the FULL config matrix (>= 18 cells
        # covering dpsgd/eventgrad/sp x masked|compact x arena x
        # obs/chaos/integrity x bucketed, ON the production geometries
        # — LeNetCifar, ResNet18, transformer full+flash — alongside
        # the MLP base) reports ZERO violations with exact wire-byte
        # truth, EVERY seeded oracle violation (rank coupling, dtype
        # upcast, extra ravel, byte-formula drift, host callback, conv
        # rank-merge, unregistered kernel, attention cross-rank gather)
        # is flagged, and the AST lint rules pass repo-wide. The ISSUE
        # 16 extension adds the partitioned trigger-policy cells
        # (micro/hybrid x masked|compact x f32/int8, partition offsets
        # declared + checked) and the partition_overlap oracle; the
        # ISSUE 17 extension adds the carrier-resident cells
        # (masked-int8 + compact-bf16, EventState.bufs held in the wire
        # dtype) and the stale_scale_reuse oracle; the ISSUE 20
        # extension adds the composed overlap-stack cells (bucketed K=4
        # x staleness=2 x compact-int8 x carrier-resident, plus
        # sp_eventgrad's payload queues at D=2) and the
        # bucket_queue_skew oracle: >= 30 cells, >= 14 oracles
        "n_configs": {"type": "integer", "minimum": 30},
        "n_clean": {"type": "integer", "minimum": 30},
        "configs": {"type": "array", "minItems": 30, "items": _AUDIT_CELL},
        # the distinct audit geometries the matrix covered: all four
        "models": {"type": "array", "minItems": 4},
        "n_oracles": {"type": "integer", "minimum": 14},
        "n_detected": {"type": "integer", "minimum": 14},
        "oracles": {
            "type": "array",
            "minItems": 13,
            "items": {
                "type": "object",
                "required": ["name", "detected"],
                "properties": {
                    "name": {"type": "string"},
                    "detected": {"enum": [True]},
                },
            },
        },
        "lint_violations": {"enum": [0]},
        "wall_s": {"type": "number", "minimum": 0},
    },
}

_LEDGER_ROUND = {
    "type": "object",
    "required": ["round", "source", "status", "provenance"],
    "properties": {
        "round": {"type": "integer", "minimum": 1},
        "source": {"type": "string"},
        "status": {"enum": ["ok", "no-data"]},
        "provenance": {"type": ["string", "null"]},
        "step_ms": {"type": ["number", "null"], "minimum": 0},
        "mfu": {"type": ["number", "null"], "minimum": 0},
        "roofline_bound": {"enum": ["compute", "memory", None]},
        "mfu_source": {"enum": ["record", "costmodel", None]},
    },
}

STRAGGLER_ABLATION_SCHEMA = {
    "type": "object",
    "required": [
        "bench", "schema_version", "topo", "algo", "chaos", "straggler",
        "legs", "lockstep_step_time", "bounded_async_step_time",
        "speedup_vs_lockstep", "bounded_async_beats_lockstep",
        "acc_gap_pt", "replay_bitwise", "measured", "measured_ratio",
        "measured_lockstep_wall_s", "measured_bounded_wall_s",
        "measured_agrees_with_modeled", "wall_s",
    ],
    "properties": {
        "bench": {"enum": ["straggler_ablation"]},
        "schema_version": {"type": "integer", "minimum": 1},
        "topo": {"type": "string"},
        "algo": {"enum": ["eventgrad"]},
        # the injected straggler: the rank whose sends arrive late and
        # by how many passes (the chaos dict carries the full schedule)
        "chaos": {"type": "object"},
        "straggler": {
            "type": "object",
            "required": ["rank", "lag"],
            "properties": {
                "rank": {"type": "integer", "minimum": 0},
                "lag": {"type": "integer", "minimum": 2},
            },
        },
        # the bounded-async acceptance gates (ISSUE 15): under the
        # injected persistent straggler, at least one lockstep
        # (staleness <= 1) and one bounded-async (D >= 2) leg ran;
        # bounded-async STRICTLY beats the lockstep's modeled step
        # time, holds accuracy within 0.5 pt, and every bounded leg
        # replays bitwise from its seed — a committed artifact
        # violating any of these is a schema violation
        "legs": {
            "type": "array",
            "minItems": 2,
            "items": {
                "type": "object",
                "required": [
                    "staleness", "lockstep", "modeled_step_time",
                    "test_accuracy",
                ],
                "properties": {
                    "staleness": {"type": "integer", "minimum": 0},
                    "lockstep": {"type": "boolean"},
                    "modeled_step_time": {"type": "number", "minimum": 0},
                    "test_accuracy": {"type": "number", "minimum": 0},
                    "replay_bitwise": {"enum": [True]},
                    "late_commits": {"type": "integer", "minimum": 0},
                    "edge_staleness_max": {"type": "integer", "minimum": 0},
                },
            },
        },
        "lockstep_step_time": {"type": "number", "minimum": 0},
        "bounded_async_step_time": {"type": "number", "minimum": 0},
        "speedup_vs_lockstep": {"type": "number", "minimum": 1.0},
        "bounded_async_beats_lockstep": {"enum": [True]},
        "acc_gap_pt": {"type": "number", "minimum": 0, "maximum": 0.5},
        "replay_bitwise": {"enum": [True]},
        # the measured wall-clock leg (ISSUE 20): a threaded per-rank
        # executor runs the composed config's calibrated per-pass
        # compute against a busy-wait-throttled straggler and times
        # lockstep vs bounded-async on a REAL clock. A committed
        # artifact claiming `measured: true` must show the lockstep
        # strictly slower (measured_ratio > 1 — minimum 1.0 is the
        # schema's floor, the tool itself refuses == 1.0) AND agreeing
        # in direction with the modeled leg
        "measured": {"enum": [True]},
        "measured_config": {"type": "string"},
        "measured_passes": {"type": "integer", "minimum": 1},
        "measured_compute_s": {"type": "number", "minimum": 0},
        "measured_lockstep_staleness": {"type": "integer", "minimum": 0},
        "measured_bounded_staleness": {"type": "integer", "minimum": 2},
        "measured_lockstep_wall_s": {"type": "number", "minimum": 0},
        "measured_bounded_wall_s": {"type": "number", "minimum": 0},
        "measured_ratio": {"type": "number", "minimum": 1.0},
        "measured_agrees_with_modeled": {"enum": [True]},
        "wall_s": {"type": "number", "minimum": 0},
    },
}

_LEDGER_TOTALS = {
    "type": "object",
    # the full disposition taxonomy (obs/schema.py LEDGER_COUNTER_ROWS),
    # every row EXERCISED: a composed run whose chaos/integrity/
    # capacity/async machinery left a row at zero proves nothing about
    # that row's accounting
    "required": [
        "proposed", "suppressed", "deferred", "fired", "delivered",
        "dropped", "rejected", "late_committed",
    ],
    "properties": {
        name: {"type": "integer", "minimum": 1}
        for name in (
            "proposed", "suppressed", "deferred", "fired", "delivered",
            "dropped", "rejected", "late_committed",
        )
    },
}

LEDGER_CONSERVATION_SCHEMA = {
    "type": "object",
    "required": [
        "bench", "schema_version", "topo", "algo", "op_point", "chaos",
        "integrity", "windows", "totals", "in_flight_final",
        "conservation", "dispositions_exercised",
        "all_dispositions_exercised", "leak_oracles",
        "all_leaks_caught", "obs_off_deterministic",
        "obs_off_matches_obs_run", "wall_s",
    ],
    "properties": {
        "bench": {"enum": ["ledger_conservation"]},
        "schema_version": {"type": "integer", "minimum": 1},
        "topo": {"type": "string"},
        "algo": {"enum": ["eventgrad"]},
        "op_point": {"type": "object"},
        "chaos": {"type": "string"},
        "integrity": {"type": "object"},
        # the message-lifecycle acceptance gates (ISSUE 18): every flush
        # window's conservation audit held with INTEGER equality (zero
        # violations), the run-total sender and receiver identities
        # hold, every disposition of the taxonomy was exercised, BOTH
        # seeded leak oracles were caught by the auditor, and obs="off"
        # is bitwise untouched by the ledger
        "windows": {
            "type": "array",
            "minItems": 1,
            "items": {
                "type": "object",
                "required": ["epoch", "ledger", "audit_ok"],
                "properties": {
                    "epoch": {"type": "integer", "minimum": 1},
                    "ledger": {"type": "object"},
                    "audit_ok": {"enum": [True]},
                },
            },
        },
        "totals": _LEDGER_TOTALS,
        "in_flight_final": {"type": "integer", "minimum": 0},
        "conservation": {
            "type": "object",
            "required": [
                "checks", "violations", "all_windows_ok",
                "sender_identity_run_total",
                "receiver_identity_run_total",
            ],
            "properties": {
                "checks": {"type": "integer", "minimum": 1},
                "violations": {"enum": [0]},
                "all_windows_ok": {"enum": [True]},
                "sender_identity_run_total": {"enum": [True]},
                "receiver_identity_run_total": {"enum": [True]},
            },
        },
        "dispositions_exercised": {"type": "object"},
        "all_dispositions_exercised": {"enum": [True]},
        "leak_oracles": {
            "type": "array",
            "minItems": 2,
            "items": {
                "type": "object",
                "required": ["leak", "caught", "violated_laws"],
                "properties": {
                    "leak": {
                        "enum": ["uncounted_drop", "double_reject"],
                    },
                    "caught": {"enum": [True]},
                    "violated_laws": {"type": "array", "minItems": 1},
                },
            },
        },
        "all_leaks_caught": {"enum": [True]},
        "obs_off_deterministic": {"enum": [True]},
        "obs_off_matches_obs_run": {"enum": [True]},
        "wall_s": {"type": "number", "minimum": 0},
    },
}

FRONTIER_SCHEMA = {
    "type": "object",
    "required": [
        "bench", "schema_version", "topo", "model", "op_point",
        "n_params", "capacity", "legs", "n_policies", "n_wire_dtypes",
        "policy_acc_gaps", "acc_gap_pt", "micro_below_topk_bytes",
        "replay_bitwise", "wall_s",
    ],
    "properties": {
        "bench": {"enum": ["frontier"]},
        "schema_version": {"type": "integer", "minimum": 1},
        "topo": {"type": "string"},
        "model": {"type": "string"},
        "op_point": {"type": "object"},
        "n_params": {"type": "integer", "minimum": 1},
        # the shared capacity point: micro/hybrid's compact budget and
        # the topk_percent pin both derive from the largest static
        # partition, so the bytes gate compares wires, not budgets
        "capacity": {"type": "integer", "minimum": 1},
        # the frontier acceptance gates (ISSUE 16): >= 4 policies x
        # >= 2 wire dtypes of real train() legs; micro's measured
        # bytes/step STRICTLY below topk's at every wire dtype (the
        # index-free partitioned wire is the whole claim); each
        # policy's accuracy spread across wire dtypes <= 0.5 pt (dtype
        # is a bytes knob, not an accuracy knob); every f32 leg
        # replays bitwise from its seed — a committed artifact
        # violating any of these is a schema violation
        "legs": {
            "type": "array",
            "minItems": 8,
            "items": {
                "type": "object",
                "required": [
                    "policy", "wire", "algo",
                    "bytes_per_step_per_chip", "test_accuracy",
                ],
                "properties": {
                    "policy": {"type": "string"},
                    "wire": {"enum": ["f32", "bf16", "int8"]},
                    "algo": {"enum": ["eventgrad", "sp_eventgrad"]},
                    "bytes_per_step_per_chip": {
                        "type": "number", "minimum": 0,
                    },
                    "test_accuracy": {"type": "number", "minimum": 0},
                    "replay_bitwise": {"enum": [True]},
                },
            },
        },
        "n_policies": {"type": "integer", "minimum": 4},
        "n_wire_dtypes": {"type": "integer", "minimum": 2},
        "policy_acc_gaps": {"type": "object"},
        "acc_gap_pt": {"type": "number", "minimum": 0, "maximum": 0.5},
        "micro_below_topk_bytes": {"enum": [True]},
        "replay_bitwise": {"enum": [True]},
        "wall_s": {"type": "number", "minimum": 0},
    },
}

RESIDENT_ABLATION_SCHEMA = {
    "type": "object",
    "required": [
        "bench", "schema_version", "op_point", "results", "step_ratio",
        "analytic_bytes_f32", "analytic_bytes_carrier",
        "analytic_bytes_drop_pct", "consumer_bytes_f32",
        "consumer_bytes_carrier", "consumer_bytes_drop_pct",
        "roofline_frac_f32", "roofline_frac_carrier", "bitwise_state",
        "platform",
    ],
    "properties": {
        "bench": {"enum": ["resident_ablation"]},
        "schema_version": {"type": "integer", "minimum": 1},
        "op_point": {"type": "object"},
        "results": {"type": "object"},
        # the carrier-residency acceptance gates (ISSUE 17): the
        # buffer-consumer subsystem (receive-dequant + commit select +
        # mix read, traced from the production collectives by
        # obs/costmodel.py) moves >= 25% fewer analytic HBM bytes when
        # the buffers stay in the int8 carrier; the WHOLE-step analytic
        # bytes also drop strictly (the step total is dominated by
        # trigger/gate-pack/grad/optimizer traffic residency never
        # touches, so its percentage is structurally diluted); the
        # scanned median-paired step ratio shows the dequant fusion is
        # free on CPU; and the carrier leg's final TrainState + scanned
        # metrics equal the f32-resident leg's bitwise — a committed
        # artifact violating any of these is a schema violation
        "step_ratio": {"type": "number", "minimum": 0, "maximum": 1.02},
        "analytic_bytes_f32": {"type": "number", "minimum": 1},
        "analytic_bytes_carrier": {"type": "number", "minimum": 1},
        "analytic_bytes_drop_pct": {
            "type": "number", "minimum": 1e-9, "maximum": 100,
        },
        "consumer_bytes_f32": {"type": "number", "minimum": 1},
        "consumer_bytes_carrier": {"type": "number", "minimum": 1},
        "consumer_bytes_drop_pct": {
            "type": "number", "minimum": 25, "maximum": 100,
        },
        "roofline_frac_f32": {"type": "number", "minimum": 0},
        "roofline_frac_carrier": {"type": "number", "minimum": 0},
        "bitwise_state": {"enum": [True]},
        "platform": {"type": "string"},
    },
}

PERF_LEDGER_SCHEMA = {
    "type": "object",
    "required": [
        "bench", "schema_version", "n_rounds", "rounds_with_mfu",
        "rounds", "multichip", "ablations", "gates", "gates_all_ok",
    ],
    "properties": {
        "bench": {"enum": ["perf_ledger"]},
        "schema_version": {"type": "integer", "minimum": 1},
        # the perf-ledger acceptance gates (ISSUE 11): every existing
        # BENCH round is in the trajectory (r01's stalled round rides
        # as an explicit no-data entry), at least the five data rounds
        # carry a populated MFU (record-carried on chip rounds,
        # cost-model-backfilled on CPU rounds), and EVERY
        # ratio-vs-previous-round regression gate passes — a committed
        # ledger with a failing gate is a schema violation, so a perf
        # regression cannot land silently
        "n_rounds": {"type": "integer", "minimum": 6},
        "rounds_with_mfu": {"type": "integer", "minimum": 5},
        "rounds": {"type": "array", "minItems": 6, "items": _LEDGER_ROUND},
        "gates": {
            "type": "array",
            "items": {
                "type": "object",
                "required": [
                    "metric", "kind", "threshold", "prev_round", "round",
                    "ratio", "ok",
                ],
                "properties": {
                    "ok": {"type": "boolean"},
                    "ratio": {"type": "number"},
                },
            },
        },
        "gates_all_ok": {"enum": [True]},
        "multichip": {"type": "array"},
        "ablations": {"type": "object"},
    },
}

#: artifacts/ families with real schemas (filename prefix match); every
#: other artifacts/*.json only needs to parse into an object/array
_ARTIFACT_FAMILIES = (
    ("audit_", AUDIT_SCHEMA),
    ("crash_matrix_", CRASH_MATRIX_SCHEMA),
    ("integrity_", INTEGRITY_SCHEMA),
    ("obs_report_", OBS_REPORT_SCHEMA),
    ("obs_overhead_", OBS_OVERHEAD_SCHEMA),
    ("arena_ablation_", ARENA_ABLATION_SCHEMA),
    ("bucketed_ablation_", BUCKETED_ABLATION_SCHEMA),
    ("mesh_ablation_", MESH_ABLATION_SCHEMA),
    ("pipeline_bubble_", PIPELINE_BUBBLE_SCHEMA),
    ("resident_ablation_", RESIDENT_ABLATION_SCHEMA),
    ("bench_direct_best_", _METRIC_LINE),
    ("bench_supervised_", _METRIC_LINE),
    ("frontier_", FRONTIER_SCHEMA),
    ("ledger_conservation_", LEDGER_CONSERVATION_SCHEMA),
    ("perf_ledger", PERF_LEDGER_SCHEMA),
    ("soak_", SOAK_SCHEMA),
    ("straggler_ablation_", STRAGGLER_ABLATION_SCHEMA),
    ("tpu_flagship", FLAGSHIP_SCHEMA),
)


def _schema_for_artifact(name: str) -> Dict[str, Any]:
    for prefix, schema in _ARTIFACT_FAMILIES:
        if name.startswith(prefix):
            return schema
    return _ANY_RECORD


def validate_json_file(path: str, schema: Dict[str, Any]) -> List[str]:
    name = os.path.basename(path)
    try:
        with open(path) as f:
            instance = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return [f"{name}: unreadable/invalid JSON: {e}"]
    return [f"{name}{e[1:]}" for e in validate(instance, schema)]


def validate_jsonl_file(
    path: str, line_schema: Optional[Dict[str, Any]] = None,
) -> List[str]:
    """Every non-empty line must parse as a JSON object (the JsonlLogger
    contract); `line_schema` tightens per-line checks where a family has
    one."""
    errs: List[str] = []
    name = os.path.basename(path)
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError as e:
        return [f"{name}: unreadable: {e}"]
    for i, line in enumerate(lines, 1):
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError as e:
            errs.append(f"{name}:{i}: invalid JSON: {e}")
            continue
        errs.extend(
            f"{name}:{i}{e[1:]}"
            for e in validate(rec, line_schema or {"type": "object"})
        )
    return errs


def validate_repo(root: str) -> Dict[str, Any]:
    """Validate every committed JSON/JSONL evidence file under `root`;
    returns {"checked": [...], "errors": [...]}."""
    checked: List[str] = []
    errors: List[str] = []

    def check(path, fn, *a):
        checked.append(os.path.relpath(path, root))
        errors.extend(fn(path, *a))

    for path in sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))):
        check(path, validate_json_file, BENCH_SCHEMA)
    for path in sorted(glob.glob(os.path.join(root, "MULTICHIP_r*.json"))):
        check(path, validate_json_file, MULTICHIP_SCHEMA)
    base = os.path.join(root, "BASELINE.json")
    if os.path.exists(base):
        check(base, validate_json_file,
              {"type": "object", "required": ["metric"]})
    kern = os.path.join(root, "KERNELS_TPU.json")
    if os.path.exists(kern):  # despite the name, a JSONL stream
        check(kern, validate_jsonl_file)
    for path in sorted(glob.glob(os.path.join(root, "artifacts", "*.json"))):
        check(path, validate_json_file,
              _schema_for_artifact(os.path.basename(path)))
    for path in sorted(glob.glob(os.path.join(root, "artifacts", "*.jsonl"))):
        check(path, validate_jsonl_file)
    return {"checked": checked, "errors": errors}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--root",
        default=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    args = ap.parse_args(argv)
    out = validate_repo(args.root)
    for e in out["errors"]:
        print(e, file=sys.stderr)
    print(
        f"validated {len(out['checked'])} files, "
        f"{len(out['errors'])} errors"
    )
    return 1 if out["errors"] else 0


if __name__ == "__main__":
    sys.exit(main())
