"""Nested-jaxpr traversal + the op-accounting the regression gates use.

One walker for every consumer (the arena op-count gate in
tests/test_arena.py, the hygiene checks in analysis/audit.py, ad-hoc
prints in tools/): `iter_eqns` yields every equation of a jaxpr
INCLUDING those inside nested call/scan/cond/while/pjit/custom-deriv
sub-jaxprs, so a count or a search can never silently miss ops that
jit/scan wrapping moved one level down.
"""

from __future__ import annotations

from typing import Iterator, Optional, Tuple

import jax


def sub_jaxprs(eqn) -> Iterator["jax.core.Jaxpr"]:
    """Every jaxpr nested in an equation's params (pjit's `jaxpr`,
    scan/while/cond bodies, custom_jvp/vjp call jaxprs, pallas_call
    KERNEL bodies — the `jaxpr` param is a bare Jaxpr, so the cost
    model and auditor both see inside Pallas kernels; index-map jaxprs
    buried in opaque GridMapping objects are intentionally not pytree
    leaves and stay out), as bare `jax.core.Jaxpr` objects.
    tests/test_audit.py::test_walker_counts_through_pallas pins the
    pallas nesting."""
    for v in eqn.params.values():
        for sub in jax.tree.leaves(
            v,
            is_leaf=lambda x: isinstance(
                x, (jax.core.Jaxpr, jax.core.ClosedJaxpr)
            ),
        ):
            if isinstance(sub, jax.core.ClosedJaxpr):
                yield sub.jaxpr
            elif isinstance(sub, jax.core.Jaxpr):
                yield sub


def iter_eqns(
    jaxpr: "jax.core.Jaxpr", path: Tuple[str, ...] = ()
) -> Iterator[Tuple["jax.core.JaxprEqn", Tuple[str, ...]]]:
    """(eqn, path) for every equation, depth-first through every nested
    sub-jaxpr. `path` names the enclosing primitives (e.g.
    ('scan', 'pjit')) so findings can say WHERE they sit."""
    for eqn in jaxpr.eqns:
        yield eqn, path
        sub_path = path + (eqn.primitive.name,)
        for sub in sub_jaxprs(eqn):
            yield from iter_eqns(sub, sub_path)


def count_primitives(jaxpr, name: Optional[str] = None) -> int:
    """Total equation count (or occurrences of primitive `name`)
    including every nested sub-jaxpr."""
    return sum(
        1 for eqn, _ in iter_eqns(jaxpr) if name is None or eqn.primitive.name == name
    )


def count_full_ravels(jaxpr, n_total: int) -> int:
    """Concatenates materializing a full [n_total] model buffer — the
    per-step footprint of a pytree flatten (the arena op budget's unit;
    under the vmap lift the buffer is [n_ranks, n_total], so the check
    reads the TRAILING dim)."""
    total = 0
    for eqn, _ in iter_eqns(jaxpr):
        if (
            eqn.primitive.name == "concatenate"
            and eqn.outvars[0].aval.shape
            and eqn.outvars[0].aval.shape[-1] == n_total
        ):
            total += 1
    return total


def bucket_schedule(jaxpr, wire_dims, commit_dims) -> dict:
    """Machine-check of the bucketed gossip schedule's emission order
    (ISSUE 10 acceptance gate): in the vmap-lifted bucketed step's
    jaxpr, at least one EXCHANGE-side op of bucket k must appear
    between UPDATE-side ops of buckets k-1 and k+1 — the exchanges
    interleave with the update work instead of forming one prefix
    block (the monolithic shape).

    Detection (structural signatures of the vmap lift, where every
    per-rank array is [n_ranks, dim]):
      * exchange-side: a `gather` whose output shape equals its operand
        shape — the ROW PERMUTATION `lax.ppermute` lowers to under vmap
        — whose index operand has shape (n_ranks, 1) (one source row
        per rank), with trailing dim == wire_dims[b]: the bucket's
        value lane. Data-dependent unpack/expansion gathers carry
        per-POSITION indices ([dim, 1]) and never match.
      * update-side: a rank-batched ([n_ranks, dim], ndim == 2)
        `select_n` with trailing dim == commit_dims[b] — the buffer
        commit's `where(eff[seg], cand, stale)` — that appears AFTER
        the bucket's first exchange op. The temporal filter is what
        makes the attribution sound on single-leaf buckets: a commit
        consumes the exchange's output and can never precede it, while
        the wire-build mask `where(fire_k, leaf, 0)` (leaf-sized, so
        it collides with the commit dim exactly when the bucket is one
        leaf) is an exchange INPUT and always precedes it — so a
        prefix-block emission keeps zero update ops between exchanges
        and cannot false-pass the gate.

    `wire_dims` / `commit_dims` are the per-bucket trailing dims of the
    value lane and the commit select; each list must be collision-free
    (pairwise distinct) or the attribution is refused. Returns
    {"exchange": {b: [ordinal, ...]}, "update": {...},
    "interleaved": bool, "witnesses": [(k, ordinal), ...]}."""
    wire_dims = [int(d) for d in wire_dims]
    commit_dims = [int(d) for d in commit_dims]
    if len(set(wire_dims)) != len(wire_dims):
        raise ValueError(f"wire_dims collide: {wire_dims}")
    if len(set(commit_dims)) != len(commit_dims):
        raise ValueError(f"commit_dims collide: {commit_dims}")
    n_buckets = len(wire_dims)
    ex: dict = {b: [] for b in range(n_buckets)}
    upd: dict = {b: [] for b in range(n_buckets)}
    for ordinal, (eqn, _path) in enumerate(iter_eqns(jaxpr)):
        name = eqn.primitive.name
        out_aval = eqn.outvars[0].aval
        shape = tuple(getattr(out_aval, "shape", ()) or ())
        if len(shape) != 2:
            continue
        if name == "gather" and len(eqn.invars) >= 2:
            in_aval = eqn.invars[0].aval
            idx_shape = tuple(
                getattr(getattr(eqn.invars[1], "aval", None), "shape", ())
                or ()
            )
            if (
                shape == tuple(in_aval.shape)
                and idx_shape == (shape[0], 1)
                and shape[-1] in wire_dims
            ):
                ex[wire_dims.index(shape[-1])].append(ordinal)
        elif name == "select_n" and shape[-1] in commit_dims:
            upd[commit_dims.index(shape[-1])].append(ordinal)
    # temporal soundness filter (docstring): only selects AFTER the
    # bucket's first exchange can be its commit — wire-build masks
    # (which may share the dim on single-leaf buckets) precede it
    for b in range(n_buckets):
        if ex[b]:
            first_ex = min(ex[b])
            upd[b] = [o for o in upd[b] if o > first_ex]
        else:
            upd[b] = []
    witnesses = []
    for k in range(1, n_buckets - 1):
        if not (ex[k] and upd[k - 1] and upd[k + 1]):
            continue
        lo, hi = min(upd[k - 1]), max(upd[k + 1])
        for e in ex[k]:
            if lo < e < hi:
                witnesses.append((k, e))
                break
    return {
        "exchange": ex,
        "update": upd,
        "interleaved": bool(witnesses),
        "witnesses": witnesses,
    }


def primitive_census(jaxpr) -> dict:
    """{primitive name: count} over every nested equation — the
    inventory view `tools/audit.py --census` prints."""
    out: dict = {}
    for eqn, _ in iter_eqns(jaxpr):
        out[eqn.primitive.name] = out.get(eqn.primitive.name, 0) + 1
    return out
