"""Checkpoint/resume — absent from the reference (no torch::save anywhere;
the consensus model is evaluated then dropped, event.cpp:517-586). Cheap win
on TPU: orbax snapshots of the full stacked TrainState (params, optimizer
moments, event thresholds/slopes/buffers, sparsifier replicas, PRNG keys),
so an interrupted decentralized run resumes with its exact gossip state.
"""

from __future__ import annotations

import os
import shutil
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp


def save(path: str, state: Any) -> None:
    """Crash-safe snapshot: write to `<path>.tmp`, swap the old snapshot to
    `<path>.prev`, promote tmp, drop prev. A kill at any point leaves either
    `<path>` or `<path>.prev` complete — `latest()` finds whichever survived.

    Multi-process: EVERY process must call this (orbax coordinates the write
    internally and only the primary touches disk); `path` must be on a
    filesystem all processes can read for a later resume. Leaves must be
    host-replicated (numpy) — `multihost.to_host` the state first."""
    from eventgrad_tpu.parallel import multihost

    path = os.path.abspath(path)
    tmp, prev = path + ".tmp", path + ".prev"
    # force=True clears a stale tmp itself, primary-only with internal syncs
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(tmp, state, force=True)
    if multihost.is_primary():
        if os.path.exists(path):
            # make room for the demotion; the current snapshot covers the gap
            if os.path.exists(prev):
                shutil.rmtree(prev)
            os.rename(path, prev)
        # the promoted snapshot may be absent (first save, or resumed from
        # .prev); never touch a surviving .prev until the new one is in place
        os.rename(tmp, path)
        if os.path.exists(prev):
            shutil.rmtree(prev)
    multihost.barrier("eg-ckpt-promote")


def latest(path: str) -> Optional[str]:
    """The newest complete snapshot for `path` (the primary, or the .prev
    left by a save interrupted mid-swap); None if neither exists."""
    path = os.path.abspath(path)
    for cand in (path, path + ".prev"):
        if os.path.exists(cand):
            return cand
    return None


def restore(path: str, template: Any) -> Any:
    """Restore into the structure of `template` (an abstract or concrete
    TrainState with the same shapes/dtypes)."""
    path = os.path.abspath(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        target = jax.tree.map(ocp.utils.to_shape_dtype_struct, template)
        return ckptr.restore(path, item=target)
