from eventgrad_tpu.ops.attention import (
    flash_attention,
    flash_attention_lse,
    flash_attention_reference,
)
from eventgrad_tpu.ops.fused_update import fused_mix_sgd, mix_sgd_reference
from eventgrad_tpu.ops.arena_update import fused_mix_commit, mix_commit_reference
from eventgrad_tpu.ops.event_engine import (
    event_propose_pack,
    masked_wire,
    masked_wire_reference,
)
