"""Attribute the EventGraD-vs-D-PSGD wall overhead (round-3 verdict item 2).

BENCH_r03 recorded wall_s_eventgrad/wall_s_dpsgd = 80.1/60.5 (1.32x) at the
reduced tier — but wall_s wraps the whole train() call, jit compile
included, so the ratio conflates one-time compile cost with per-step cost.
This tool separates them at the same op-point (LeNetCifar, Ring(8), global
batch 64, synthetic CIFAR prototypes), then microbenches each candidate
component of the event step in isolation:

  full steps   compile_s + steady-state step_ms for
                 dpsgd            dense exchange, no trigger
                 event_adaptive   the bench trigger (horizon 1.05 + guard)
                 event_constant   constant threshold — drops the adaptive
                                  slope/history machinery
  micro (ms)   jit'd alone on the same shapes/topology:
                 decide           the trigger state machine
                                  (events.decide_and_update: per-leaf norms
                                  + [L]-vector threshold update)
                 exchange_dense   collectives.neighbor_vals (dpsgd's path)
                 exchange_masked  collectives.masked_neighbor_vals
                                  (mask + fire-bit ppermute + where-select)
                 mix_sgd_tail     mix + optax SGD tail (shared)

Derived: per-step overhead %, compile-time delta, and the projected wall
attribution at the bench's 640-pass op-point. Reference point for scale:
the reference's trigger is ~8 scalar norms/step (dmnist/event/event.cpp:
316-343) — near-free; the TPU rebuild's should be too.

Writes artifacts/overhead_ablation_r4_<platform>.json.

Usage:
  python tools/overhead_ablation.py [n_timed_steps]   micro attribution
  python tools/overhead_ablation.py arena [n_timed_steps]
      flat-arena A/B (the --arena on|off leg): times the eventgrad and
      dpsgd steps at the bench op-point with the arena OFF (legacy tree
      path) and ON (flat-arena engine, parallel/arena.py), and writes
      artifacts/arena_ablation_<platform>.json with the
      step_overhead_ratio (eventgrad/dpsgd) before and after — the
      acceptance metric of the flat-arena PR (target: <= 1.05 with
      bitwise-equivalent training, tests/test_arena.py). Validated by
      tools/validate_artifacts.py.
  python tools/overhead_ablation.py bucketed [n_rounds]
      bucketed-gossip-schedule A/B (the --bucketed K leg, ISSUE 10):
      times the eventgrad arena step at the bench op-point under the
      monolithic schedule (K=1) and the bucketed schedule (K in
      {2, 4, 8}), scanned + interleaved with MEDIAN PAIRED per-round
      ratios (the only step-timing protocol stable on this shared
      CPU), machine-checks the jaxpr interleaving gate
      (analysis/walker.bucket_schedule: bucket k's exchange ops sit
      between buckets k-1/k+1's update ops instead of forming one
      prefix block), and writes artifacts/bucketed_ablation_<platform>
      .json — schema-gated (BUCKETED_ABLATION_SCHEMA: headline K=4
      ratio <= 1.02, jaxpr_interleaved true, bitwise_state true).
  python tools/overhead_ablation.py resident [n_rounds]
      carrier-resident gossip-state A/B (the carrier_resident=True
      leg): times the eventgrad compact-int8 step at the bench
      op-point with the receive buffers f32-RESIDENT vs
      CARRIER-RESIDENT (stored int8 + per-leaf dequant scales, the
      dequant fused into the commit/mix reads), same scanned +
      median-paired protocol, with the analytic HBM bytes/step and
      roofline_frac of BOTH traced programs (obs/costmodel.py) next
      to the timings, and writes artifacts/resident_ablation_
      <platform>.json — schema-gated (RESIDENT_ABLATION_SCHEMA:
      analytic bytes drop >= 25%, step ratio <= 1.02, bitwise_state
      true).
  python tools/overhead_ablation.py order <ed|de>     in-loop order twin:
      runs the bench op-point's two train() legs in the given order
      (ed = eventgrad first, the bench's order; de = dpsgd first) inside
      THIS process and appends one JSON line per leg to
      artifacts/overhead_order_r4_<platform>.jsonl. Run each order in a
      fresh process: the experiment exists to expose what the FIRST
      train() call of a process absorbs (jit/backend warmup) — the
      round-3 bench's 1.32x wall ratio, measured with eventgrad always
      first, turned out to be exactly that.
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402

from eventgrad_tpu.utils import compile_cache  # noqa: E402

compile_cache.honor_cpu_pin()
# persistent XLA cache: the A/B legs re-run this entry point per process
# and must not re-pay the jit compile (no-op on the CPU backend)
compile_cache.enable()

from eventgrad_tpu.data.datasets import load_or_synthesize  # noqa: E402
from eventgrad_tpu.data.sharding import batched_epoch  # noqa: E402
from eventgrad_tpu.models import LeNetCifar  # noqa: E402
from eventgrad_tpu.parallel import collectives  # noqa: E402
from eventgrad_tpu.parallel.events import (  # noqa: E402
    EventConfig, decide_and_update,
)
from eventgrad_tpu.parallel.spmd import spmd  # noqa: E402
from eventgrad_tpu.parallel.topology import Ring  # noqa: E402
from eventgrad_tpu.train.state import init_train_state  # noqa: E402
from eventgrad_tpu.train.steps import make_train_step  # noqa: E402
from eventgrad_tpu.utils.profiling import timed_steps  # noqa: E402


from eventgrad_tpu.utils.metrics import median as _median  # noqa: E402


def _micro(fn, *args, iters: int = 30):
    """(compile_s, steady ms/call) of jit'd fn on fixed args."""
    t0 = time.perf_counter()
    jax.block_until_ready(fn(*args))
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return compile_s, 1000 * (time.perf_counter() - t0) / iters


def order_experiment(order: str) -> None:
    """Time the reduced-tier train() twins in the given order, one JSON
    line per leg (see module docstring)."""
    import numpy as np

    from eventgrad_tpu.train.loop import train

    topo = Ring(8)
    x, y = load_or_synthesize("cifar10", None, "train", n_synth=1024)
    cfg = EventConfig(
        adaptive=True, horizon=1.05, warmup_passes=10, max_silence=50
    )
    common = dict(
        epochs=40, batch_size=8, learning_rate=1e-2, momentum=0.9,
        random_sampler=True, log_every_epoch=False,
    )
    d = jax.devices()[0]
    out_path = os.path.join(
        REPO, "artifacts", f"overhead_order_r4_{d.platform}.jsonl"
    )
    algos = ("eventgrad", "dpsgd") if order == "ed" else ("dpsgd", "eventgrad")
    for pos, algo in enumerate(algos):
        t0 = time.perf_counter()
        _, hist = train(
            LeNetCifar(), topo, x, y, algo=algo,
            event_cfg=cfg if algo == "eventgrad" else None, **common,
        )
        wall = time.perf_counter() - t0
        steady = hist[1:] or hist
        rec = {
            "order": order, "position": pos, "algo": algo,
            "wall_s": round(wall, 2),
            "epoch0_s": round(hist[0]["wall_s"], 2),
            "steady_step_ms": round(1000 * float(
                np.mean([h["wall_s"] / h["steps"] for h in steady])
            ), 2),
            "passes": common["epochs"] * hist[0]["steps"],
            "platform": d.platform,
            "captured_at": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
            ),
        }
        with open(out_path, "a") as f:
            f.write(json.dumps(rec) + "\n")
        print(json.dumps(rec), flush=True)


def arena_experiment(n_rounds: int = 8) -> None:
    """A/B the flat-arena engine at the bench op-point (module docstring).

    Measurement protocol: each (algo, arena) variant compiles ONE
    scan-of-16-steps program (the production dispatch shape train()
    runs — per-call step timing re-executes loop-invariant work the
    real scan hoists and is dominated by dispatch jitter), then the
    four programs run INTERLEAVED for `n_rounds` rounds with the
    per-round minimum kept — back-to-back interleaving cancels the
    machine's load drift, which single-leg timing on a shared CPU does
    not. step_ms is min-of-rounds / 16."""
    topo = Ring(8)
    model = LeNetCifar()
    lr, mom = 1e-2, 0.9
    tx = optax.sgd(lr, momentum=mom)
    per_rank = 8
    K = 16
    x, y = load_or_synthesize("cifar10", None, "train", n_synth=1024)
    xb, yb = batched_epoch(x, y, topo.n_ranks, per_rank)
    import numpy as np

    xs = jnp.asarray(np.stack(
        [xb[:, s % xb.shape[1]] for s in range(K)], 0))
    ys = jnp.asarray(np.stack(
        [yb[:, s % yb.shape[1]] for s in range(K)], 0))
    cfg = EventConfig(
        adaptive=True, horizon=1.05, warmup_passes=10, max_silence=50
    )

    variants = {}
    for algo, c in (("dpsgd", None), ("eventgrad", cfg)):
        for arena_on in (False, True):
            state = init_train_state(
                model, x.shape[1:], tx, topo, algo, c, arena=arena_on
            )
            lifted = spmd(make_train_step(
                model, tx, topo, algo, event_cfg=c, arena=arena_on,
            ), topo)

            def run(s, xs, ys, _l=lifted):
                return jax.lax.scan(lambda s, b: _l(s, b), s, (xs, ys))

            run = jax.jit(run)
            t0 = time.perf_counter()
            out, _ = run(state, xs, ys)
            jax.block_until_ready(out.params)
            compile_s = time.perf_counter() - t0
            variants[(algo, arena_on)] = (state, run, compile_s)

    times = {k: [] for k in variants}
    for _ in range(n_rounds):
        for k, (state, run, _c) in variants.items():
            t0 = time.perf_counter()
            out, _ = run(state, xs, ys)
            jax.block_until_ready(out.params)
            times[k].append((time.perf_counter() - t0) / K * 1000)

    results = {}
    for arena_on in (False, True):
        leg = {}
        for algo in ("dpsgd", "eventgrad"):
            v = times[(algo, arena_on)]
            leg[algo] = {
                "compile_s": round(variants[(algo, arena_on)][2], 4),
                "step_ms_min": round(min(v), 4),
                "step_ms_p50": round(_median(v), 4),
            }
        # PAIRED estimator: the two algos of one round run back-to-back
        # under the same machine load, so the per-round ratio cancels
        # load drift that min/median of the individual legs cannot; the
        # median across rounds is the committed number
        paired = [
            e / d
            for e, d in zip(times[("eventgrad", arena_on)],
                            times[("dpsgd", arena_on)])
        ]
        leg["step_overhead_ratio"] = round(_median(paired), 4)
        results["arena_on" if arena_on else "arena_off"] = leg
        print(json.dumps({("arena_on" if arena_on else "arena_off"): leg}),
              flush=True)

    # secondary leg: per-DISPATCH step timing (one jit call per step, no
    # scan) — the regime where the r05 1.10x event overhead reproduces
    # on CPU (loop-invariant work re-executes per call and nothing
    # amortizes). Recorded so the two regimes can't be conflated.
    import jax as _jax

    b1 = (xs[0], ys[0])
    steps1 = {}
    for (algo, arena_on), (state, _run, _c) in variants.items():
        c = cfg if algo == "eventgrad" else None
        step = _jax.jit(spmd(make_train_step(
            model, tx, topo, algo, event_cfg=c, arena=arena_on,
        ), topo))
        s2, _ = step(state, b1)
        _jax.block_until_ready(s2.params)
        steps1[(algo, arena_on)] = (state, step)
    times1 = {k: [] for k in steps1}
    for _ in range(n_rounds):
        for k, (state, step) in steps1.items():
            s = state
            t0 = time.perf_counter()
            for _ in range(6):
                s, _ = step(s, b1)
            _jax.block_until_ready(s.params)
            times1[k].append((time.perf_counter() - t0) / 6 * 1000)
    per_dispatch = {}
    for arena_on in (False, True):
        key = "arena_on" if arena_on else "arena_off"
        paired = [
            e / d
            for e, d in zip(times1[("eventgrad", arena_on)],
                            times1[("dpsgd", arena_on)])
        ]
        per_dispatch[key] = {
            "dpsgd_step_ms_min": round(min(times1[("dpsgd", arena_on)]), 4),
            "eventgrad_step_ms_min": round(
                min(times1[("eventgrad", arena_on)]), 4
            ),
            "step_overhead_ratio": round(_median(paired), 4),
        }
    print(json.dumps({"per_dispatch": per_dispatch}), flush=True)

    d = jax.devices()[0]
    rec = {
        "bench": "arena_ablation",
        "op_point": {
            "model": "LeNetCifar", "topology": "ring8",
            "global_batch": topo.n_ranks * per_rank,
            "scan_steps": K, "rounds": n_rounds, "momentum": mom,
            "trigger": {"horizon": 1.05, "max_silence": 50, "warmup": 10},
        },
        "results": results,
        "per_dispatch": per_dispatch,
        "overhead_ratio_before": results["arena_off"]["step_overhead_ratio"],
        "overhead_ratio_after": results["arena_on"]["step_overhead_ratio"],
        "note": (
            "ratios are median paired per-round (eventgrad/dpsgd "
            "back-to-back under the same load) over scanned "
            "steady-state runs — the production dispatch shape and "
            "bench.py's metric. On this shared CPU both land near the "
            "~1-2% measurement floor; before/after differences inside "
            "that band are noise, and the acceptance bound is the "
            "arena-on value. The r05 1.10x overhead reproduces on CPU "
            "mainly in the per_dispatch regime (also recorded)."
        ),
        "eventgrad_step_speedup": round(
            results["arena_off"]["eventgrad"]["step_ms_min"]
            / results["arena_on"]["eventgrad"]["step_ms_min"], 4
        ),
        "platform": d.platform,
        "device_kind": d.device_kind,
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    out_path = os.path.join(
        REPO, "artifacts", f"arena_ablation_{d.platform}.json"
    )
    with open(out_path, "w") as f:
        json.dump(rec, f, indent=1)
        f.write("\n")
    print(json.dumps(rec, indent=1))


def bucketed_experiment(n_rounds: int = 24) -> None:
    """A/B the bucketed gossip schedule at the bench op-point (module
    docstring): same scanned/interleaved/median-paired protocol as
    `arena_experiment`, with the monolithic (K=1) leg as the paired
    denominator of every bucketed leg."""
    import numpy as np

    from eventgrad_tpu.analysis import walker
    from eventgrad_tpu.parallel import arena

    topo = Ring(8)
    model = LeNetCifar()
    lr, mom = 1e-2, 0.9
    tx = optax.sgd(lr, momentum=mom)
    per_rank = 8
    K_SCAN = 16
    x, y = load_or_synthesize("cifar10", None, "train", n_synth=1024)
    xb, yb = batched_epoch(x, y, topo.n_ranks, per_rank)
    xs = jnp.asarray(np.stack(
        [xb[:, s % xb.shape[1]] for s in range(K_SCAN)], 0))
    ys = jnp.asarray(np.stack(
        [yb[:, s % yb.shape[1]] for s in range(K_SCAN)], 0))
    cfg = EventConfig(
        adaptive=True, horizon=1.05, warmup_passes=10, max_silence=50
    )

    sweep = (1, 2, 4, 8)
    variants = {}
    finals = {}  # compile-pass outputs double as the bitwise gate
    for k in sweep:
        state = init_train_state(
            model, x.shape[1:], tx, topo, "eventgrad", cfg,
            arena=True, bucketed=k,
        )
        lifted = spmd(make_train_step(
            model, tx, topo, "eventgrad", event_cfg=cfg, arena=True,
            bucketed=(k if k > 1 else None),
        ), topo)

        def run(s, xs, ys, _l=lifted):
            return jax.lax.scan(lambda s, b: _l(s, b), s, (xs, ys))

        run = jax.jit(run)
        t0 = time.perf_counter()
        out, _ = run(state, xs, ys)
        jax.block_until_ready(out.params)
        variants[k] = (state, run, round(time.perf_counter() - t0, 4))
        finals[k] = jax.tree.leaves(out.params)

    # bitwise gate rides the measurement: every bucketed leg's final
    # scanned state must equal the monolithic leg's exactly
    bitwise = all(
        all(
            np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(finals[1], finals[k])
        )
        for k in sweep[1:]
    )

    times = {k: [] for k in sweep}
    for _ in range(n_rounds):
        for k, (state, run, _c) in variants.items():
            t0 = time.perf_counter()
            out, _ = run(state, xs, ys)
            jax.block_until_ready(out.params)
            times[k].append((time.perf_counter() - t0) / K_SCAN * 1000)

    results = {}
    for k in sweep:
        leg = {
            "compile_s": variants[k][2],
            "step_ms_min": round(min(times[k]), 4),
            "step_ms_p50": round(_median(times[k]), 4),
        }
        if k > 1:
            paired = [b / m for b, m in zip(times[k], times[1])]
            leg["overhead_ratio_vs_monolithic"] = round(_median(paired), 4)
        results[f"k{k}"] = leg
        print(json.dumps({f"k{k}": leg}), flush=True)

    # jaxpr interleaving gate at the headline K=4: at least one
    # exchange-side op of bucket k sits between update-side ops of
    # buckets k-1 and k+1 (analysis/walker.bucket_schedule)
    gate_k = 4
    st4 = variants[gate_k][0]
    params0 = jax.tree.map(lambda l: l[0], st4.params)
    buckets = arena.arena_spec(params0).buckets(gate_k)
    dims = [b.size for b in buckets]
    step4 = make_train_step(
        model, tx, topo, "eventgrad", event_cfg=cfg, arena=True,
        bucketed=gate_k,
    )
    closed = jax.make_jaxpr(spmd(step4, topo))(st4, (xs[0], ys[0]))
    sched = walker.bucket_schedule(closed.jaxpr, dims, dims)

    d = jax.devices()[0]
    rec = {
        "bench": "bucketed_ablation",
        "op_point": {
            "model": "LeNetCifar", "topology": "ring8",
            "global_batch": topo.n_ranks * per_rank,
            "scan_steps": K_SCAN, "rounds": n_rounds, "momentum": mom,
            "trigger": {"horizon": 1.05, "max_silence": 50, "warmup": 10},
            "k_sweep": list(sweep),
        },
        "results": results,
        # the acceptance headline: bucketed K=4 vs monolithic, median
        # paired per-round over scanned steady-state runs (<= 1.02)
        "overhead_ratio": results["k4"]["overhead_ratio_vs_monolithic"],
        "bitwise_state": bool(bitwise),
        "jaxpr_interleaved": bool(sched["interleaved"]),
        "jaxpr_witnesses": [list(w) for w in sched["witnesses"]],
        "bucket_sizes_k4": dims,
        "note": (
            "ratios are median paired per-round (bucketed/monolithic "
            "back-to-back under the same load) over scanned "
            "steady-state runs. On CPU the schedule change is a wash "
            "inside the ~1-2% noise floor — the overlap win needs real "
            "async transfers (TPU ICI); this proxy bounds the schedule "
            "OVERHEAD, and the jaxpr gate proves the emission actually "
            "interleaves exchange and update work."
        ),
        "platform": d.platform,
        "device_kind": d.device_kind,
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    out_path = os.path.join(
        REPO, "artifacts", f"bucketed_ablation_{d.platform}.json"
    )
    with open(out_path, "w") as f:
        json.dump(rec, f, indent=1)
        f.write("\n")
    print(json.dumps(rec, indent=1))


def resident_experiment(n_rounds: int = 24) -> None:
    """A/B the carrier-resident gossip state at the bench op-point
    (eventgrad + compact int8, LeNetCifar/Ring(8)): same scanned/
    interleaved/median-paired protocol as `bucketed_experiment`, with
    the f32-resident leg as the paired denominator.

    Carrier-resident (train(carrier_resident=True)) keeps the
    EventState receive buffers in the WIRE dtype — int8 carriers plus
    per-leaf dequant scales in EventState.buf_scales — and fuses the
    dequant into the commit/mix reads, so the resident buffer traffic
    drops from 4 B/elem/neighbor to ~1. Three gates ride the
    measurement (RESIDENT_ABLATION_SCHEMA, tools/validate_artifacts.py):

      * bitwise_state — the carrier leg's final scanned TrainState
        equals the f32-resident leg's exactly (buffers compared through
        collectives.dequant_carrier_bufs, the f32 view);
      * consumer_bytes_drop_pct >= 25 — analytic HBM bytes
        (obs.costmodel.analyze_jaxpr) of the buffer-CONSUMER subsystem:
        receive-dequant -> commit select -> mix read, traced from the
        exact production collectives at this op-point. This is the
        subsystem residency changes — the f32 leg dequants the wire at
        receive and then re-reads 4 B/elem on every commit and mix,
        the carrier leg moves 1 B carriers plus [L]-sized scales;
      * analytic_bytes_drop_pct > 0 — the WHOLE-step analytic bytes
        also drop (strictly), reported transparently next to the
        consumer number: the step total is dominated by trigger /
        gate-pack / grad / optimizer traffic residency never touches,
        so the whole-step percentage is structurally diluted (~9% at
        this op-point); roofline_frac moves with it;
      * step_ratio <= 1.02 — the dequant fusion costs nothing
        measurable on CPU (median paired per-round, scanned).
    """
    import numpy as np

    from eventgrad_tpu.obs import costmodel
    from eventgrad_tpu.obs.devicespec import spec_for_kind
    from eventgrad_tpu.parallel import arena

    topo = Ring(8)
    model = LeNetCifar()
    lr, mom = 1e-2, 0.9
    tx = optax.sgd(lr, momentum=mom)
    per_rank = 8
    K_SCAN = 16
    WIRE, CAP = "int8", 48000  # the frontier op-point's compact budget
    x, y = load_or_synthesize("cifar10", None, "train", n_synth=1024)
    xb, yb = batched_epoch(x, y, topo.n_ranks, per_rank)
    xs = jnp.asarray(np.stack(
        [xb[:, s % xb.shape[1]] for s in range(K_SCAN)], 0))
    ys = jnp.asarray(np.stack(
        [yb[:, s % yb.shape[1]] for s in range(K_SCAN)], 0))
    cfg = EventConfig(
        adaptive=True, horizon=1.05, warmup_passes=10, max_silence=50
    )

    legs = ("f32_resident", "carrier_resident")
    variants = {}
    finals = {}
    for leg in legs:
        carrier = leg == "carrier_resident"
        state = init_train_state(
            model, x.shape[1:], tx, topo, "eventgrad", cfg, arena=True,
            resident_wire=(WIRE if carrier else None),
        )
        lifted = spmd(make_train_step(
            model, tx, topo, "eventgrad", event_cfg=cfg, arena=True,
            wire=WIRE, gossip_wire="compact", compact_capacity=CAP,
            carrier_resident=carrier,
        ), topo)

        def run(s, xs, ys, _l=lifted):
            return jax.lax.scan(lambda s, b: _l(s, b), s, (xs, ys))

        run = jax.jit(run)
        t0 = time.perf_counter()
        out, ms = run(state, xs, ys)
        jax.block_until_ready(out.params)
        variants[leg] = (state, run, round(time.perf_counter() - t0, 4))
        finals[leg] = (out, ms)

    # bitwise gate rides the measurement: FULL final state + the
    # scanned per-step metrics (buffers compared in their f32 view)
    s_f, m_f = finals["f32_resident"]
    s_c, m_c = finals["carrier_resident"]
    spec0 = arena.arena_spec(jax.tree.map(lambda l: l[0], s_f.params))
    if s_c.event.buf_scales is not None:
        deq = jax.vmap(lambda b, s: collectives.dequant_carrier_bufs(
            b, s, spec0))(s_c.event.bufs, s_c.event.buf_scales)
    else:
        deq = jax.vmap(lambda b: collectives.dequant_carrier_bufs(
            b, None, spec0))(s_c.event.bufs)
    pairs = (
        list(zip(jax.tree.leaves(s_f.params), jax.tree.leaves(s_c.params)))
        + list(zip(jax.tree.leaves(s_f.opt_state),
                   jax.tree.leaves(s_c.opt_state)))
        + [(getattr(s_f.event, f), getattr(s_c.event, f))
           for f in ("thres", "last_sent_norm", "last_sent_iter",
                     "slopes", "num_events", "num_deferred")]
        + list(zip(jax.tree.leaves(s_f.event.bufs), jax.tree.leaves(deq)))
        + [(m_f[k], m_c[k]) for k in m_f]
    )
    bitwise = all(
        np.array_equal(np.asarray(a), np.asarray(b)) for a, b in pairs
    )

    # the buffer-consumer subsystem A/B: everything downstream of the
    # wire payload that touches the resident buffers — receive-dequant
    # (f32 leg only; the carrier leg stores the payload as-is), the
    # commit wide-select, and the mix read — traced from the SAME
    # production collectives the step runs, single rank (the rank vmap
    # scales both legs identically)
    params0 = jax.tree.map(lambda l: l[0], s_f.params)
    n_nb = topo.n_neighbors
    n, L = spec0.n_total, spec0.n_leaves
    wire_q = tuple(jnp.zeros((n,), jnp.int8) for _ in range(n_nb))
    wire_s = tuple(jnp.ones((L,), jnp.float32) for _ in range(n_nb))
    eff0 = tuple(jnp.zeros((L,), bool) for _ in range(n_nb))

    def consumer_f32(p, wq, ws, effs, lasts):
        cands = collectives.dequant_carrier_bufs(wq, ws, spec0)
        nb = collectives.commit_bufs_flat(cands, effs, lasts, spec0)
        return collectives.mix_flat_into_tree(p, nb, spec0, topo), nb

    def consumer_car(p, wq, ws, effs, lasts, lscales):
        nb = collectives.commit_bufs_flat(wq, effs, lasts, spec0)
        ns = collectives.commit_carrier_scales(ws, effs, lscales)
        return collectives.mix_carrier_flat_into_tree(
            p, nb, ns, spec0, topo
        ), nb, ns

    cons_f32 = costmodel.analyze_jaxpr(jax.make_jaxpr(consumer_f32)(
        params0, wire_q, wire_s, eff0,
        tuple(jnp.zeros((n,), jnp.float32) for _ in range(n_nb)),
    ))["hbm_bytes_total"]
    cons_car = costmodel.analyze_jaxpr(jax.make_jaxpr(consumer_car)(
        params0, wire_q, wire_s, eff0,
        tuple(jnp.zeros((n,), jnp.int8) for _ in range(n_nb)),
        tuple(jnp.ones((L,), jnp.float32) for _ in range(n_nb)),
    ))["hbm_bytes_total"]

    times = {leg: [] for leg in legs}
    for _ in range(n_rounds):
        for leg, (state, run, _c) in variants.items():
            t0 = time.perf_counter()
            out, _ = run(state, xs, ys)
            jax.block_until_ready(out.params)
            times[leg].append((time.perf_counter() - t0) / K_SCAN * 1000)

    # analytic HBM bytes/step + roofline at each leg's own layout (the
    # cost model traces the SAME step program the timing ran)
    d = jax.devices()[0]
    dev_spec = spec_for_kind(d.platform, d.device_kind)
    analytic = {}
    for leg in legs:
        cm = costmodel.analyze_step(
            model, tx, topo, "eventgrad", cfg, x, y, per_rank,
            variants[leg][0], wire=WIRE, gossip_wire="compact",
            compact_capacity=CAP,
            carrier_resident=(leg == "carrier_resident"),
        )
        rl = costmodel.roofline(
            cm["flops_total"], cm["hbm_bytes_total"],
            _median(times[leg]) / 1000.0, dev_spec,
        )
        analytic[leg] = (cm, rl)

    results = {}
    for leg in legs:
        cm, rl = analytic[leg]
        results[leg] = {
            "resident_dtype": WIRE if leg == "carrier_resident" else "f32",
            "compile_s": variants[leg][2],
            "step_ms_min": round(min(times[leg]), 4),
            "step_ms_p50": round(_median(times[leg]), 4),
            "hbm_bytes_per_step": cm["hbm_bytes_total"],
            "flops_per_step": cm["flops_total"],
            "arithmetic_intensity": rl["arithmetic_intensity"],
            "roofline_bound": rl["roofline_bound"],
            "roofline_frac": rl["roofline_frac"],
        }
    paired = [c / f for c, f in
              zip(times["carrier_resident"], times["f32_resident"])]
    results["carrier_resident"]["overhead_ratio_vs_f32"] = round(
        _median(paired), 4
    )
    print(json.dumps(results, indent=1), flush=True)

    b_f32 = analytic["f32_resident"][0]["hbm_bytes_total"]
    b_car = analytic["carrier_resident"][0]["hbm_bytes_total"]
    rec = {
        "bench": "resident_ablation",
        "schema_version": 1,
        "op_point": {
            "model": "LeNetCifar", "topology": "ring8",
            "global_batch": topo.n_ranks * per_rank,
            "scan_steps": K_SCAN, "rounds": n_rounds, "momentum": mom,
            "wire": WIRE, "gossip_wire": "compact",
            "compact_capacity": CAP,
            "trigger": {"horizon": 1.05, "max_silence": 50, "warmup": 10},
        },
        "results": results,
        # the acceptance headline: carrier vs f32-resident step time,
        # median paired per-round over scanned steady-state runs
        "step_ratio": results["carrier_resident"]["overhead_ratio_vs_f32"],
        "analytic_bytes_f32": b_f32,
        "analytic_bytes_carrier": b_car,
        "analytic_bytes_drop_pct": round(100.0 * (1.0 - b_car / b_f32), 2),
        "consumer_bytes_f32": cons_f32,
        "consumer_bytes_carrier": cons_car,
        "consumer_bytes_drop_pct": round(
            100.0 * (1.0 - cons_car / cons_f32), 2
        ),
        "roofline_frac_f32": results["f32_resident"]["roofline_frac"],
        "roofline_frac_carrier":
            results["carrier_resident"]["roofline_frac"],
        "bitwise_state": bool(bitwise),
        "note": (
            "step ratios are median paired per-round (carrier/f32 "
            "back-to-back under the same load) over scanned "
            "steady-state runs; on CPU the dequant fusion is a wash "
            "inside the noise floor — the BYTES columns are the claim, "
            "measured analytically on the same traced programs "
            "(obs/costmodel.py). consumer_bytes_* is the buffer-"
            "consumer subsystem residency changes (receive-dequant + "
            "commit select + mix read, traced from the production "
            "collectives); analytic_bytes_* is the whole step, whose "
            "percentage is diluted by trigger/gate-pack/grad/optimizer "
            "traffic residency never touches. The bitwise gate proves "
            "the carrier layout changes no trained value"
        ),
        "platform": d.platform,
        "device_kind": d.device_kind,
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    out_path = os.path.join(
        REPO, "artifacts", f"resident_ablation_{d.platform}.json"
    )
    with open(out_path, "w") as f:
        json.dump(rec, f, indent=1)
        f.write("\n")
    print(json.dumps(rec, indent=1))


def main() -> None:
    if len(sys.argv) > 1 and sys.argv[1] == "order":
        order_experiment(sys.argv[2] if len(sys.argv) > 2 else "ed")
        return
    if len(sys.argv) > 1 and sys.argv[1] == "arena":
        arena_experiment(int(sys.argv[2]) if len(sys.argv) > 2 else 24)
        return
    if len(sys.argv) > 1 and sys.argv[1] == "bucketed":
        bucketed_experiment(int(sys.argv[2]) if len(sys.argv) > 2 else 24)
        return
    if len(sys.argv) > 1 and sys.argv[1] == "resident":
        resident_experiment(int(sys.argv[2]) if len(sys.argv) > 2 else 24)
        return
    n_steps = int(sys.argv[1]) if len(sys.argv) > 1 else 24
    topo = Ring(8)
    model = LeNetCifar()
    tx = optax.sgd(1e-2, momentum=0.9)
    per_rank = 8  # global batch 64 over 8 ranks — the reduced-tier op-point

    x, y = load_or_synthesize("cifar10", None, "train", n_synth=1024)
    xb, yb = batched_epoch(x, y, topo.n_ranks, per_rank)
    steps_avail = xb.shape[1]
    batches = [
        (jnp.asarray(xb[:, s % steps_avail]), jnp.asarray(yb[:, s % steps_avail]))
        for s in range(n_steps)
    ]

    cfg_adapt = EventConfig(
        adaptive=True, horizon=1.05, warmup_passes=10, max_silence=50
    )
    cfg_const = EventConfig(adaptive=False, constant=0.05, warmup_passes=10)

    full = {}
    for name, algo, cfg in (
        ("dpsgd", "dpsgd", None),
        ("event_adaptive", "eventgrad", cfg_adapt),
        ("event_constant", "eventgrad", cfg_const),
    ):
        state = init_train_state(model, x.shape[1:], tx, topo, algo, cfg)
        step = jax.jit(
            spmd(make_train_step(model, tx, topo, algo, event_cfg=cfg), topo)
        )
        out = timed_steps(step, state, batches, warmup=2)
        out.pop("state")
        full[name] = {k: round(v, 4) for k, v in out.items()}

    # ---- micro benches on the same stacked shapes -----------------------
    st = init_train_state(model, x.shape[1:], tx, topo, "eventgrad", cfg_adapt)
    params, ev = st.params, st.event

    decide = jax.jit(spmd(
        lambda p, s: decide_and_update(
            p, s, jnp.int32(100), cfg_adapt, topo.n_neighbors
        ),
        topo,
    ))
    ex_dense = jax.jit(spmd(
        lambda p: collectives.neighbor_vals(p, topo), topo
    ))
    ex_masked = jax.jit(spmd(
        lambda p, f, b: collectives.masked_neighbor_vals(p, f, b, topo)[0],
        topo,
    ))

    def _tail(p, bufs, g, o):
        mixed = collectives.mix(p, bufs, topo)
        updates, o2 = tx.update(g, o, mixed)
        return optax.apply_updates(mixed, updates), o2

    tail = jax.jit(spmd(_tail, topo))

    fire, ev2 = decide(params, ev)
    jax.block_until_ready(fire)
    grads = jax.tree.map(jnp.ones_like, params)

    micro = {}
    for name, fn, args in (
        ("decide", decide, (params, ev)),
        ("exchange_dense", ex_dense, (params,)),
        ("exchange_masked", ex_masked, (params, fire, ev.bufs)),
        ("mix_sgd_tail", tail, (params, ev.bufs, grads, st.opt_state)),
    ):
        compile_s, ms = _micro(fn, *args)
        micro[name] = {"compile_s": round(compile_s, 4), "ms": round(ms, 4)}

    dp, ea = full["dpsgd"], full["event_adaptive"]
    passes = 640  # the reduced tier's captured op-point
    step_delta_ms = ea["step_ms_mean"] - dp["step_ms_mean"]
    compile_delta_s = ea["compile_s"] - dp["compile_s"]
    derived = {
        "step_overhead_pct": round(
            100 * (ea["step_ms_mean"] / dp["step_ms_mean"] - 1), 2
        ),
        "compile_delta_s": round(compile_delta_s, 2),
        "projected_wall_delta_s_at_640_passes": round(
            compile_delta_s + passes * step_delta_ms / 1000, 2
        ),
        "micro_trigger_share_of_step_pct": round(
            100 * micro["decide"]["ms"] / ea["step_ms_mean"], 2
        ),
        "micro_masked_minus_dense_ms": round(
            micro["exchange_masked"]["ms"] - micro["exchange_dense"]["ms"], 4
        ),
    }

    d = jax.devices()[0]
    rec = {
        "op_point": {
            "model": "LeNetCifar", "topology": "ring8",
            "global_batch": topo.n_ranks * per_rank,
            "n_timed_steps": n_steps,
            "trigger": {"horizon": 1.05, "max_silence": 50, "warmup": 10},
        },
        "full_steps": full,
        "micro": micro,
        "derived": derived,
        "platform": d.platform,
        "device_kind": d.device_kind,
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    out_path = os.path.join(
        REPO, "artifacts", f"overhead_ablation_r4_{d.platform}.json"
    )
    with open(out_path, "w") as f:
        json.dump(rec, f, indent=1)
    print(json.dumps(rec, indent=1))


if __name__ == "__main__":
    main()
