"""Elastic recovery: the supervisor detects crashes and hangs, restarts
from the latest snapshot, and the recovered run finishes the job with the
exact trajectory of an uninterrupted one. (The reference has no failure
story: a dead rank blocks its peers' MPI_Recv forever, decent.cpp:200-205.)"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cli_args(tmp, tag, extra):
    return [
        "--algo", "eventgrad", "--mesh", "ring:4", "--dataset", "synthetic",
        "--model", "mlp", "--epochs", "3", "--batch-size", "8",
        "--n-synth", "128", "--warmup-passes", "2",
        "--log-file", os.path.join(tmp, f"{tag}.jsonl"),
    ] + extra


def _run_supervised(tmp, tag, extra, timeout=0.0, max_restarts=3,
                    cli_args=None):
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    cmd = [
        sys.executable, "-m", "eventgrad_tpu.supervise",
        "--timeout", str(timeout), "--max-restarts", str(max_restarts), "--",
    ] + (cli_args if cli_args is not None else _cli_args(tmp, tag, extra))
    return subprocess.run(
        cmd, cwd=REPO, env=env, capture_output=True, text=True, timeout=600
    )


def _records(tmp, tag):
    with open(os.path.join(tmp, f"{tag}.jsonl")) as f:
        return [json.loads(l) for l in f]


def test_crash_recovery_matches_uninterrupted_run(tmp_path):
    tmp = str(tmp_path)
    ck = os.path.join(tmp, "ck")

    straight = _run_supervised(tmp, "straight", ["--checkpoint-dir",
                                                 os.path.join(tmp, "ck0"),
                                                 "--save-every", "1"])
    assert straight.returncode == 0, straight.stderr[-2000:]

    # crash:1 kills the child (exit 13) right after epoch 1's snapshot; the
    # supervisor must relaunch with --resume and epochs 2-3 must replay the
    # uninterrupted trajectory exactly
    crashed = _run_supervised(
        tmp, "crashed",
        ["--checkpoint-dir", ck, "--save-every", "1",
         "--fault-inject", "crash:1"],
    )
    assert crashed.returncode == 0, crashed.stderr[-2000:]
    assert "attempt 1 failed (exit code 13)" in crashed.stderr

    ref = _records(tmp, "straight")
    got = _records(tmp, "crashed")
    # log has epoch 1 (first attempt) then epochs 2,3 + final (second)
    assert [r.get("epoch") for r in got] == [1, 2, 3, None]
    by_epoch = {r["epoch"]: r for r in ref if "epoch" in r}
    for r in got[:-1]:
        np.testing.assert_allclose(r["loss"], by_epoch[r["epoch"]]["loss"],
                                   atol=1e-6)
        assert r["num_events"] == by_epoch[r["epoch"]]["num_events"]
    assert got[-1]["final"] and ref[-1]["final"]
    np.testing.assert_allclose(got[-1]["accuracy"], ref[-1]["accuracy"],
                               atol=1e-6)


def test_hang_detection_kills_and_recovers(tmp_path):
    tmp = str(tmp_path)
    hung = _run_supervised(
        tmp, "hung",
        ["--checkpoint-dir", os.path.join(tmp, "ck"), "--save-every", "1",
         "--fault-inject", "hang:1"],
        timeout=45.0, max_restarts=1,
    )
    assert hung.returncode == 0, hung.stderr[-2000:]
    assert "no heartbeat" in hung.stderr
    recs = _records(tmp, "hung")
    assert [r.get("epoch") for r in recs] == [1, 2, 3, None]


def test_supervisor_gives_up_after_max_restarts(tmp_path):
    tmp = str(tmp_path)
    # no periodic snapshots -> the resumed run restarts at epoch 1 and hits
    # the same crash every attempt: the supervisor must stop trying
    doomed = _run_supervised(
        tmp, "doomed",
        ["--checkpoint-dir", os.path.join(tmp, "ck"),
         "--fault-inject", "crash:1"],
        max_restarts=1,
    )
    assert doomed.returncode == 13
    assert "giving up" in doomed.stderr


def test_supervisor_requires_checkpoint_dir(tmp_path):
    with pytest.raises(SystemExit):
        from eventgrad_tpu.supervise import supervise

        supervise(["--algo", "dpsgd"])


# --- sliding restart-budget window + backoff (ISSUE 6 satellite) --------


def test_restart_budget_lifetime_and_window():
    from eventgrad_tpu.supervise import RestartBudget

    # window 0 = lifetime counter (legacy --max-restarts semantics)
    clock = iter(float(t) for t in range(100)).__next__
    b = RestartBudget(2, 0.0, now=clock)
    assert b.record_failure() and b.record_failure()
    assert not b.record_failure()  # 3rd failure ever: escalate

    # sliding window: old failures roll off, a once-a-day crasher lives
    times = iter([0.0, 5.0, 100.0, 103.0, 106.0]).__next__
    w = RestartBudget(2, 10.0, now=times)
    assert w.record_failure()          # t=0
    assert w.record_failure()          # t=5: 2 in window, at budget
    assert w.record_failure()          # t=100: both rolled off
    assert w.record_failure()          # t=103: 2 in window
    assert not w.record_failure()      # t=106: 3 within 10s -> escalate
    with pytest.raises(ValueError):
        RestartBudget(-1)


def test_backoff_delay_doubles_caps_and_jitters():
    from eventgrad_tpu.supervise import backoff_delay

    import random

    no_jit = [backoff_delay(k, base=1.0, cap=8.0, jitter=0.0)
              for k in range(1, 7)]
    assert no_jit == [1.0, 2.0, 4.0, 8.0, 8.0, 8.0]  # doubles, then caps
    assert backoff_delay(3, base=0.0) == 0.0  # disabled
    assert backoff_delay(0) == 0.0
    rng = random.Random(7)
    jittered = backoff_delay(2, base=1.0, cap=8.0, jitter=0.5, rng=rng)
    assert 2.0 <= jittered <= 3.0  # 2 * (1 + 0.5*U[0,1))


class _FakeProc:
    """A child that exits instantly with a scripted return code."""

    def __init__(self, rc):
        self.returncode = rc

    def poll(self):
        return self.returncode


def _run_fake_supervise(monkeypatch, rcs, **kw):
    """Drive supervise() against scripted child exits; returns
    (final rc, argv per attempt, backoff sleeps)."""
    from eventgrad_tpu import supervise as sup

    codes = iter(rcs)
    launches, sleeps = [], []

    def fake_popen(cmd, *a, **k):
        launches.append(cmd)
        return _FakeProc(next(codes))

    monkeypatch.setattr(sup.subprocess, "Popen", fake_popen)
    clock = iter(float(t) for t in range(0, 10000, kw.pop("dt", 1))).__next__
    rc = sup.supervise(
        ["--checkpoint-dir", "/tmp/nonexistent-ck"],
        _now=clock, _sleep=sleeps.append, **kw,
    )
    return rc, launches, sleeps


def test_supervise_backoff_between_relaunches(monkeypatch):
    rc, launches, sleeps = _run_fake_supervise(
        monkeypatch, [7, 7, 0], max_restarts=5,
        backoff_base=0.5, backoff_max=4.0, backoff_jitter=0.0,
    )
    assert rc == 0 and len(launches) == 3
    assert sleeps == [0.5, 1.0]  # exponential, one per failed attempt
    # every relaunch resumes from the snapshot
    assert all("--resume" in cmd for cmd in launches[1:])


def test_supervise_sliding_window_outlives_lifetime_budget(monkeypatch):
    """With a sliding window, spaced-out failures never accumulate: a
    run that fails more times than max_restarts IN TOTAL still finishes,
    as long as no window ever holds more than the budget."""
    rc, launches, _ = _run_fake_supervise(
        monkeypatch, [1, 1, 1, 0], max_restarts=1, restart_window=2.0,
        dt=5, backoff_base=0.0,
    )
    assert rc == 0 and len(launches) == 4  # 3 failures > lifetime budget

    # same failure pattern under the lifetime counter: gives up after 1
    rc2, launches2, _ = _run_fake_supervise(
        monkeypatch, [1, 1, 1, 0], max_restarts=1, restart_window=0.0,
        dt=5, backoff_base=0.0,
    )
    assert rc2 == 1 and len(launches2) == 2


def test_supervise_window_burst_escalates(monkeypatch):
    """A crash loop (failures faster than the window drains) exhausts
    the sliding budget and escalates with the child's exit code."""
    rc, launches, _ = _run_fake_supervise(
        monkeypatch, [9, 9, 9, 9], max_restarts=2, restart_window=100.0,
        dt=1, backoff_base=0.0,
    )
    assert rc == 9 and len(launches) == 3


def test_supervise_integrity_abort_gives_up_without_restart(monkeypatch):
    """Exit 77 (INTEGRITY_ABORT_EXIT) is a PERMANENT escalation: the
    divergence sentinel tripped beyond the rollback budget, so a
    relaunch would restore the same last-known-good snapshot and replay
    the same divergence. The supervisor must give up immediately —
    restart budget notwithstanding — and the constant must stay pinned
    to chaos.integrity's (supervise stays jax-free, so it re-declares
    rather than imports)."""
    from eventgrad_tpu import supervise as sup
    from eventgrad_tpu.chaos.integrity import INTEGRITY_ABORT_EXIT

    assert sup.INTEGRITY_ABORT_EXIT == INTEGRITY_ABORT_EXIT == 77
    rc, launches, sleeps = _run_fake_supervise(
        monkeypatch, [INTEGRITY_ABORT_EXIT, 0], max_restarts=5,
        backoff_base=0.0,
    )
    assert rc == INTEGRITY_ABORT_EXIT
    assert len(launches) == 1  # no restart, budget untouched
    assert sleeps == []


def test_supervise_preemption_relaunches_without_budget_charge(monkeypatch):
    """Exit 75 (PREEMPTED_EXIT) is a CLEAN preemption: the child
    drained, snapshotted, and exited on purpose, so the supervisor must
    relaunch immediately with --resume, sleep no backoff, and charge
    nothing — a spot service preempted more often than max_restarts
    must keep running forever. The constant stays pinned to the
    jax-free exit-code contract module."""
    from eventgrad_tpu import exitcodes
    from eventgrad_tpu import supervise as sup

    assert sup.PREEMPTED_EXIT == exitcodes.PREEMPTED_EXIT == 75
    # 4 preemptions against max_restarts=0: every one relaunches anyway
    rc, launches, sleeps = _run_fake_supervise(
        monkeypatch, [75, 75, 75, 75, 0], max_restarts=0,
        backoff_base=1.0, backoff_jitter=0.0,
    )
    assert rc == 0 and len(launches) == 5
    assert sleeps == []  # no backoff between preemption relaunches
    assert all("--resume" in cmd for cmd in launches[1:])


def test_supervise_preemption_resets_crash_backoff(monkeypatch):
    """A preemption between crashes resets the consecutive-failure
    exponent: the relaunch after the post-preemption crash backs off
    from the base again instead of continuing the doubling."""
    rc, launches, sleeps = _run_fake_supervise(
        monkeypatch, [7, 7, 75, 7, 0], max_restarts=5,
        backoff_base=0.5, backoff_max=8.0, backoff_jitter=0.0,
    )
    assert rc == 0 and len(launches) == 5
    # crash, crash (doubled), preemption (no sleep), crash (reset to base)
    assert sleeps == [0.5, 1.0, 0.5]


def test_crash_recovery_hybrid_lm(tmp_path):
    """Elastic recovery composes with hybrid meshes: a dp x sp
    ring-attention LM run crash-injected after epoch 1 is restarted from
    its snapshot and replays the uninterrupted trajectory exactly."""
    tmp = str(tmp_path)

    def go(tag, extra):
        lm_args = [
            "--algo", "eventgrad", "--mesh", "dp:2,sp:2",
            "--model", "transformer", "--attn", "ring",
            "--seq-len", "32", "--vocab", "64", "--dim", "32",
            "--heads", "4", "--layers", "1", "--epochs", "3",
            "--batch-size", "4", "--n-synth", "64", "--lr", "0.1",
            "--warmup-passes", "2",
            "--log-file", os.path.join(tmp, f"{tag}.jsonl"),
        ] + extra
        return _run_supervised(tmp, tag, [], cli_args=lm_args)

    straight = go("straight", ["--checkpoint-dir", os.path.join(tmp, "ck0"),
                               "--save-every", "1"])
    assert straight.returncode == 0, straight.stderr[-2000:]
    crashed = go("crashed", ["--checkpoint-dir", os.path.join(tmp, "ck1"),
                             "--save-every", "1", "--fault-inject", "crash:1"])
    assert crashed.returncode == 0, crashed.stderr[-2000:]
    # the injection must actually have fired and the supervisor restarted
    assert "attempt 1 failed (exit code 13)" in crashed.stderr

    s = [r for r in _records(tmp, "straight") if "epoch" in r]
    c = [r for r in _records(tmp, "crashed") if "epoch" in r]
    assert [r["epoch"] for r in s] == [1, 2, 3]
    assert [r["epoch"] for r in c] == [1, 2, 3]
    for rs, rc in zip(s, c):
        assert rs["num_events"] == rc["num_events"]
        np.testing.assert_allclose(rs["loss"], rc["loss"], atol=1e-6)
