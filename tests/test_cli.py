"""The flag-driven launcher (the reference's five mpirun entry points,
argv semantics from event.cpp:88-100 / spevent.cpp:47-60)."""

import json

import numpy as np
import pytest

from _spmd import requires_shard_map
from eventgrad_tpu.cli import build_parser, main, parse_mesh


def _run(capsys, args):
    assert main(args) == 0
    return [json.loads(l) for l in capsys.readouterr().out.splitlines()]


BASE = [
    "--dataset", "synthetic", "--model", "mlp", "--epochs", "2",
    "--batch-size", "8", "--n-synth", "128", "--warmup-passes", "2",
]


@pytest.mark.parametrize("algo", ["allreduce", "dpsgd", "eventgrad", "sp_eventgrad"])
def test_every_algo_runs_and_logs(capsys, algo):
    recs = _run(capsys, ["--algo", algo, "--mesh", "ring:4"] + BASE)
    epochs = [r for r in recs if "epoch" in r]
    assert [r["epoch"] for r in epochs] == [1, 2]
    for r in epochs:
        assert {"loss", "train_acc", "steps", "sent_bytes_per_step_per_chip"} <= set(r)
        if algo in ("eventgrad", "sp_eventgrad"):
            assert "msgs_saved_pct" in r and "num_events" in r
    assert recs[-1]["final"] and "accuracy" in recs[-1]


def test_torus_mesh_and_global_batch(capsys):
    recs = _run(
        capsys,
        ["--algo", "dpsgd", "--mesh", "torus:2x2", "--global-batch", "32"] + BASE,
    )
    # 128 samples / 4 ranks = 32 per rank; global batch 32 -> per-rank 8
    assert [r["steps"] for r in recs if "epoch" in r] == [4, 4]


@pytest.mark.tier1
@requires_shard_map
def test_mesh_backend_matches_sim(capsys):
    """`--backend mesh` (the shard_map lift over the 8-device CPU
    fixture) is BITWISE `--backend sim` on the full training state and
    the whole launcher record stream — not an allclose, an ==.

    Promoted into tier-1 via the explicit `tier1` marker (this module
    is otherwise slow-deselected as a launcher end-to-end suite): the
    vmap/shard_map backend parity is a core gate of the real-mesh SPMD
    backend (ROADMAP open item 1), and it once hid a standalone
    AttributeError precisely because slow-deselection kept it out of
    every tier-1 run. The deeper per-config matrix lives in
    tests/test_mesh_parity.py; this leg pins the LAUNCHER wiring — the
    `--backend` flag, the mesh build, and the record stream."""
    args = ["--algo", "eventgrad", "--mesh", "ring:8"] + BASE
    sim = _run(capsys, args + ["--backend", "sim"])
    mesh = _run(capsys, args + ["--backend", "mesh"])  # 8 virtual CPU devices
    assert len(sim) == len(mesh)
    for a, b in zip(sim, mesh):
        # every record value identical except the host-timing fields
        # and the backend stamp itself
        ka = {k: v for k, v in a.items()
              if k not in ("wall_s", "ts", "backend")}
        kb = {k: v for k, v in b.items()
              if k not in ("wall_s", "ts", "backend")}
        assert ka == kb
        if "backend" in a:
            assert (a["backend"], b["backend"]) == ("vmap", "shard_map")

    # and the FULL final state, through the train() API at the same
    # tiny geometry (the launcher records only surface aggregates)
    from eventgrad_tpu.data.datasets import synthetic_dataset
    from eventgrad_tpu.models import MLP
    from eventgrad_tpu.train.loop import train

    x, y = synthetic_dataset(128, (28, 28, 1), seed=0)
    kw = dict(algo="eventgrad", epochs=2, batch_size=8, seed=0)
    st_sim, _ = _jax_tree_states(train(MLP(hidden=16), parse_mesh("ring:8"),
                                       x, y, backend="vmap", **kw))
    st_mesh, _ = _jax_tree_states(train(MLP(hidden=16), parse_mesh("ring:8"),
                                        x, y, backend="shard_map", **kw))
    for p, q in zip(st_sim, st_mesh):
        np.testing.assert_array_equal(p, q)


def _jax_tree_states(res):
    import jax

    state, hist = res
    return [np.asarray(l) for l in jax.tree.leaves(state)], hist


def test_bad_mesh_spec_rejected():
    with pytest.raises(SystemExit):
        build_parser().parse_args(["--mesh", "hypercube:3"])
    with pytest.raises(Exception):
        parse_mesh("torus:8")


def test_reference_argv_semantics_thres_constant_zero(capsys):
    """thres_type=constant, constant=0 ==> every pass fires (exact D-PSGD),
    the reference's built-in equivalence knob (dmnist/event/README.md:59-60)."""
    recs = _run(
        capsys,
        ["--algo", "eventgrad", "--mesh", "ring:4", "--thres-mode", "constant",
         "--constant", "0", "--warmup-passes", "0", "--dataset", "synthetic",
         "--model", "mlp", "--epochs", "1", "--batch-size", "8",
         "--n-synth", "64"],
    )
    ep = [r for r in recs if "epoch" in r][0]
    assert ep["msgs_saved_pct"] == 0.0
    d = _run(
        capsys,
        ["--algo", "dpsgd", "--mesh", "ring:4", "--dataset", "synthetic",
         "--model", "mlp", "--epochs", "1", "--batch-size", "8",
         "--n-synth", "64"],
    )
    np.testing.assert_allclose(
        ep["loss"], [r for r in d if "epoch" in r][0]["loss"], atol=1e-6
    )


def test_cli_synthetic_imagenet_stress_config(capsys):
    """BASELINE's scale-stress config (ResNet-50-family EventGraD on a 2D
    torus over ImageNet-shaped data) expressed through the launcher, at
    smoke scale (--num-filters shrinks the stem; --image-size 224 and
    --num-filters 64 recover the real op-point on hardware)."""
    recs = _run(capsys, [
        "--algo", "sp_eventgrad", "--mesh", "torus:2x2", "--model", "resnet50",
        "--dataset", "synthetic-imagenet", "--image-size", "48",
        "--num-classes", "16", "--num-filters", "8", "--epochs", "1",
        "--batch-size", "4", "--n-synth", "64", "--lr", "0.01",
        "--momentum", "0.9", "--warmup-passes", "2", "--topk-percent", "10",
    ])
    assert recs[-1]["final"] and "accuracy" in recs[-1]
    assert np.isfinite(recs[0]["loss"])


def test_cli_model_knob_guard():
    with pytest.raises(SystemExit):  # width/classes knobs are resnet-only
        main(["--model", "cnn2", "--num-classes", "100"])


def test_max_silence_validation():
    with pytest.raises(SystemExit):  # negative values are rejected
        main(["--algo", "eventgrad", "--mesh", "ring:4",
              "--dataset", "synthetic", "--model", "cnn2",
              "--max-silence", "-1"])
    with pytest.raises(SystemExit):  # event-algorithm knob only
        main(["--algo", "dpsgd", "--mesh", "ring:4",
              "--dataset", "synthetic", "--model", "cnn2",
              "--max-silence", "10"])
